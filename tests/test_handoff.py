"""Cross-host KV page handoff (serve/transport.py + disagg
transport='cross_host'): wire round-trips (fp32 AND int8+scales,
bitwise), the bytes_copied>0 accounting pin, receiver-side bitwise
decode-continuation identity vs batch-1 and vs the same-host refcount
pair, receiver backlog under a tight decode pool, and the two-pool
audits. The crash/timeout protocol drills live in test_chaos_serve.py.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.serve import Request, ServeEngine
from distributed_training_guide_tpu.serve.api import generate_many
from distributed_training_guide_tpu.serve.disagg import DisaggEngine
from distributed_training_guide_tpu.serve.kv_pages import init_pages
from distributed_training_guide_tpu.serve import transport as twire

pytestmark = [pytest.mark.serve, pytest.mark.handoff, pytest.mark.disagg]


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


def _fresh(req):
    return dataclasses.replace(req, request_id=None)


def _ref(bundle, params, req, **kw):
    eng = ServeEngine(bundle, params, n_slots=1, prefix_cache=False, **kw)
    return generate_many(eng, [_fresh(req)])[0]


def _audit_pools(eng):
    """Both pools balance independently: free + held + cached ==
    capacity, with cache pages living only on the prefill side."""
    assert eng.decode_pool.n_free + sum(
        len(s.pages) for s in eng.decode.sched.slots if s is not None) \
        == eng.decode_pool.capacity
    held = sum(len(set(s.pages)) for s in eng.prefill.sched.slots
               if s is not None)
    assert eng.pool.n_free + held + eng.prefill.sched.cache_pages_held() \
        >= eng.pool.capacity - held  # shared pages overlap cache refs


# ---- wire format ------------------------------------------------------------

@pytest.mark.parametrize("kv_dtype", ["fp32", "int8"])
def test_frame_roundtrip_bitwise(kv_dtype):
    """encode -> decode reproduces every pool leaf bitwise — the int8
    pool's payload AND its fp32 scale rows both cross as raw bytes."""
    bundle = get_model("llama-debug", dtype=jnp.float32)
    pages = init_pages(bundle.config, 6, 4, kv_dtype=kv_dtype)
    key = jax.random.key(1)
    pages = jax.tree.map(
        lambda a: jax.random.normal(key, a.shape).astype(a.dtype)
        if a.dtype != jnp.int8
        else jax.random.randint(key, a.shape, -127, 127, jnp.int8), pages)
    payload = twire.gather_payload(pages, [2, 4, 1])
    frame = twire.encode_frame(7, {"cache_len": 9}, payload)
    xfer_id, header, got = twire.decode_frame(frame)
    assert xfer_id == 7 and header["cache_len"] == 9
    assert set(got) == set(payload)
    for name in payload:
        assert got[name].dtype == payload[name].dtype
        assert np.array_equal(got[name], payload[name])
    # scatter at a "receiver" pool reproduces the sender's bytes
    recv = init_pages(bundle.config, 6, 4, kv_dtype=kv_dtype)
    recv = twire.scatter_payload(recv, [1, 2, 3], payload)
    back = twire.gather_payload(recv, [1, 2, 3])
    for name in payload:
        assert np.array_equal(back[name], payload[name])


def test_frame_rejects_corruption():
    payload = {"k": np.arange(12, dtype=np.float32).reshape(1, 1, 3, 2, 2),
               "v": np.ones((1, 1, 3, 2, 2), np.float32)}
    frame = bytearray(twire.encode_frame(0, {}, payload))
    frame[len(frame) // 2] ^= 0xFF
    with pytest.raises(twire.TransportError, match="CRC"):
        twire.decode_frame(bytes(frame))
    with pytest.raises(twire.TransportError, match="short|length"):
        twire.decode_frame(bytes(frame[:-8]))


# ---- the engine-level acceptance pins ---------------------------------------

@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_crosshost_moves_real_payload_and_continues_bitwise(llama, kv_dtype):
    """The acceptance pin: every handoff ships the real serialized k/v
    payload (bytes_copied > 0 and >= the pool-leaf payload bytes), and
    the receiver-side decode continuation is token-identical to batch-1
    AND to the same-host refcount-move pair — the wire changed where the
    bytes live, not what they are."""
    bundle, params = llama
    reqs = [Request(prompt_ids=[3 + i, 17, 42, 9][:2 + (i % 3)],
                    max_new_tokens=3 + (i % 3),
                    temperature=0.8 if i % 2 else 0.0, seed=i)
            for i in range(6)]
    kw = dict(n_slots=2, n_prefill_slots=1, page_size=4, max_len=16,
              kv_dtype=kv_dtype)
    cross = DisaggEngine(bundle, params, transport="cross_host", **kw)
    res = generate_many(cross, [_fresh(r) for r in reqs],
                        max_iterations=2000)
    same = DisaggEngine(bundle, params, **kw)
    res_same = generate_many(same, [_fresh(r) for r in reqs],
                             max_iterations=2000)
    for got, via_same, req in zip(res, res_same, reqs):
        want = _ref(bundle, params, req, page_size=4, max_len=16,
                    kv_dtype=kv_dtype)
        assert got.token_ids == want.token_ids, f"seed={req.seed}"
        assert got.token_ids == via_same.token_ids
    s = cross.stats()
    assert s["handoff_bytes_copied"] > 0
    assert s["handoff_delivered"] == s["handoff_transfers"] >= 6
    assert s["handoff_dropped"] == 0
    assert s["transport"] == "cross_host"
    # payload accounting: at least one page of k+v leaf bytes per token
    # transferred crossed the wire (header/CRC ride on top)
    per_page = sum(
        np.asarray(leaf[:, :1]).nbytes if not hasattr(leaf, "q")
        else np.asarray(leaf.q[:, :1]).nbytes
        + np.asarray(leaf.scale[:, :1]).nbytes
        for leaf in (cross.pages["k"], cross.pages["v"]))
    assert s["handoff_bytes_copied"] \
        >= per_page * s["handoff_pages_transferred"]
    # post-drain audits: both pools balanced, decode pool fully free
    assert cross.decode_pool.n_free == cross.decode_pool.capacity
    assert cross.pool.n_free + cross.prefill.sched.cache_pages_held() \
        == cross.pool.capacity
    cross.close()


def test_crosshost_int8_frame_smaller_than_fp32(llama):
    """The PR-11 dividend, pinned on the wire: the int8 pool's handoff
    frames (payload + scales) are well under the fp32 pair's."""
    bundle, params = llama
    sizes = {}
    for kv in ("fp32", "int8"):
        eng = DisaggEngine(bundle, params, n_slots=1, page_size=4,
                           max_len=16, transport="cross_host", kv_dtype=kv)
        generate_many(eng, [Request(prompt_ids=[3, 17, 42, 5, 6],
                                    max_new_tokens=2)], max_iterations=500)
        sizes[kv] = eng.stats()["handoff_bytes_copied"]
        eng.close()
    assert sizes["int8"] < 0.6 * sizes["fp32"], sizes


@pytest.mark.slow
def test_crosshost_receiver_backlog_under_tight_decode_pool(llama):
    """Backlog stress (slow: the tier-1 acceptance pins live in
    test_crosshost_moves_real_payload_and_continues_bitwise): a decode
    pool too small to seat every received sequence at once:
    records wait in transit (host bytes, no pool pages), seat as decode
    slots free, and everything still completes token-identically."""
    bundle, params = llama
    eng = DisaggEngine(bundle, params, n_slots=2, n_prefill_slots=2,
                       page_size=4, max_len=16, transport="cross_host",
                       n_pages=2 * 4 + 1)   # exactly 2 slots' residency
    reqs = [Request(prompt_ids=[3 + i, 17], max_new_tokens=6, seed=i)
            for i in range(6)]
    saw_backlog = False
    ids = [eng.submit(_fresh(r)) for r in reqs]
    done = {}
    it = 0
    while eng.has_work:
        for res in eng.step():
            done[res.request_id] = res
        saw_backlog = saw_backlog or len(eng.handoff.pending) > 0
        it += 1
        assert it < 2000
    for rid, req in zip(ids, reqs):
        want = _ref(bundle, params, req, page_size=4, max_len=16)
        assert done[rid].token_ids == want.token_ids
    assert eng.decode_pool.n_free == eng.decode_pool.capacity
    eng.close()


def test_crosshost_rejects_shard_kv(llama):
    bundle, params = llama
    with pytest.raises(ValueError, match="cross_host.*shard_kv"):
        DisaggEngine(bundle, params, transport="cross_host", shard_kv=True)
    with pytest.raises(ValueError, match="transport"):
        DisaggEngine(bundle, params, transport="carrier_pigeon")


def test_crosshost_refuses_request_exceeding_decode_pool(llama):
    """submit() validates against BOTH pools: a request whose worst case
    outgrows the decode pool can never finish there and must refuse at
    the door, not preempt-loop forever."""
    from distributed_training_guide_tpu.serve import RefusalError

    bundle, params = llama
    eng = DisaggEngine(bundle, params, n_slots=1, n_prefill_slots=1,
                       page_size=4, max_len=64, transport="cross_host",
                       n_pages=3, n_prefill_pages=20)
    with pytest.raises(RefusalError, match="decode pool"):
        eng.submit(Request(prompt_ids=[3, 17], max_new_tokens=30))
    eng.close()
