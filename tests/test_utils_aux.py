"""Fast unit tests for aux subsystems: supervisor, monitor, data pipeline,
loss masking, LR schedule host mirror, error files."""
import json
import subprocess
import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np

import pytest

REPO = Path(__file__).parent.parent


# ---- loss ------------------------------------------------------------------

def test_loss_ignore_index():
    from distributed_training_guide_tpu.ops.cross_entropy import causal_lm_loss

    logits = jnp.zeros((1, 4, 8))
    labels = jnp.asarray([[1, 2, -100, 3]])
    loss = float(causal_lm_loss(logits, labels))
    # uniform logits -> log(8) per counted position, ignore masked
    np.testing.assert_allclose(loss, np.log(8), rtol=1e-6)


# ---- lr schedule host mirror ----------------------------------------------

def test_lr_at_step_matches_optax():
    import jax

    from distributed_training_guide_tpu.train.optimizer import (cosine_schedule,
                                                                lr_at_step)

    sched = cosine_schedule(3e-4, t_max=100, eta_min_ratio=0.01, warmup_steps=10)
    for step in [0, 5, 10, 50, 100, 500]:
        # device schedule computes cos in fp32; host mirror in fp64
        np.testing.assert_allclose(float(sched(step)),
                                   lr_at_step(step, 3e-4, 100, 0.01, 10),
                                   rtol=1e-3, atol=1e-10)


# ---- data pipeline ---------------------------------------------------------

def test_pipeline_local_file(tmp_path):
    from distributed_training_guide_tpu.data import (ByteTokenizer,
                                                     load_and_preprocess_data)

    corpus = tmp_path / "corpus.txt"
    corpus.write_text("hello tpu world " * 200)
    data = load_and_preprocess_data(str(corpus), ByteTokenizer(), 32)
    assert data.shape[1] == 32
    assert data.dtype == np.int32
    assert len(data) > 50


def test_pipeline_seq_clamp():
    from distributed_training_guide_tpu.data import (ByteTokenizer,
                                                     load_and_preprocess_data)

    data = load_and_preprocess_data("synthetic:10000", ByteTokenizer(), 4096,
                                    max_position_embeddings=64)
    assert data.shape[1] == 64


# ---- supervisor + error files (C19) ----------------------------------------

def test_supervisor_restarts_and_error_files(tmp_path):
    """Crash twice, then succeed — supervisor must produce per-attempt dirs,
    error.json for failures, and exit 0 overall. No jax involved."""
    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import json, os, sys
sys.path.insert(0, {str(REPO)!r})
from distributed_training_guide_tpu.launch.errors import record

state = {str(tmp_path)!r} + "/count.json"
n = json.load(open(state))["n"] if os.path.exists(state) else 0
json.dump({{"n": n + 1}}, open(state, "w"))

@record
def main():
    if n < 2:
        raise RuntimeError(f"injected fault attempt {{n}}")
    print("success")

main()
""")
    result = subprocess.run(
        [sys.executable, "-m", "distributed_training_guide_tpu.launch.supervisor",
         "--max-restarts", "3", "--log-dir", str(tmp_path / "logs"), "--",
         sys.executable, str(worker)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin", "JAX_PLATFORMS": "cpu"})
    assert result.returncode == 0, result.stdout + result.stderr
    err0 = json.loads((tmp_path / "logs/attempt_0/error.json").read_text())
    assert "injected fault attempt 0" in err0["message"]["error"]
    assert (tmp_path / "logs/attempt_2/stdout.log").read_text().strip() == "success"
    assert not (tmp_path / "logs/attempt_2/error.json").exists()


def test_supervisor_exhausts_restarts(tmp_path):
    result = subprocess.run(
        [sys.executable, "-m", "distributed_training_guide_tpu.launch.supervisor",
         "--max-restarts", "1", "--log-dir", str(tmp_path / "logs"), "--",
         sys.executable, "-c", "raise SystemExit(3)"],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert result.returncode == 3
    assert (tmp_path / "logs/attempt_1").exists()
    assert not (tmp_path / "logs/attempt_2").exists()


# ---- cluster monitor (C21) -------------------------------------------------

def test_top_cluster_local():
    result = subprocess.run(
        [sys.executable, "-m", "distributed_training_guide_tpu.monitor.top_cluster",
         "--local"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin", "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    stats = json.loads(result.stdout.strip().splitlines()[-1])
    assert len(stats["devices"]) == 8
    assert all("hbm_gb" in d for d in stats["devices"])


# ---- cluster monitor stall detection (reference hang heuristic, C21) -------

def _host_stats(host, num_allocs, hbm=4.0):
    return {"host": host, "devices": [
        {"id": 0, "kind": "fake", "hbm_gb": hbm, "hbm_peak_gb": hbm,
         "hbm_limit_gb": 16.0, "num_allocs": num_allocs}]}


def test_monitor_flags_stalled_host():
    from distributed_training_guide_tpu.monitor.top_cluster import (
        ClusterWatch, format_row)

    watch = ClusterWatch(alert_after=2)
    # busy host: allocator counters move every poll -> ok forever
    for i in range(5):
        row = watch.update(_host_stats("busy", num_allocs=100 + i))
        assert row["status"] == "ok"
    # wedged host: resident memory but frozen counters -> stalled after N
    statuses = [watch.update(_host_stats("wedged", num_allocs=42))["status"]
                for _ in range(4)]
    assert statuses == ["ok", "ok", "stalled", "stalled"]
    assert "STALLED" in format_row(watch.update(_host_stats("wedged", 42)))
    # idle host: no resident memory, frozen counters -> idle, not stalled
    for _ in range(4):
        row = watch.update(_host_stats("empty", num_allocs=0, hbm=0.0))
    assert row["status"] == "idle"
    # recovery: counters move again -> back to ok
    assert watch.update(_host_stats("wedged", num_allocs=43))["status"] == "ok"


def test_monitor_error_row():
    from distributed_training_guide_tpu.monitor.top_cluster import (
        ClusterWatch, format_row)

    row = ClusterWatch().update({"host": "gone", "error": "timeout"})
    assert row["status"] == "error"
    assert "ERROR" in format_row(row)


def test_multi_slice_mesh_fallback(eight_devices):
    """Forcing multi_slice on CPU devices (no slice_index metadata) must fall
    back to the flat mesh, not crash — the degradation path a real pod hits
    when DCN topology metadata is missing."""
    import jax

    from distributed_training_guide_tpu.parallel import make_mesh

    mesh = make_mesh(fsdp=4, multi_slice=True)
    assert mesh.shape["fsdp"] == 4 and mesh.shape["dp"] == 2
    assert mesh.devices.size == len(jax.devices())
