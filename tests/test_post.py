"""On-policy post-training runtime (post/): rollout → score → update →
publish, end to end against the serve engine.

The pins that make the loop trustworthy:

- **publish is a weight swap, not a program change**: layout-validated
  (loud failure naming the leaf), retrace-free (jit cache sizes flat
  across publishes), and decode-after-publish is BITWISE a fresh engine
  built from the published params.
- **rollouts are reproducible**: same seed + same publish schedule ⇒
  token-identical across engine restarts and spec-on/spec-off (the
  engine's position-keyed sampling streams + exact acceptance).
- **the ledger makes batches crash-recoverable**: an engine killed
  mid-rollout-batch resumes without double-counting, and the resumed
  samples are bitwise what the dead engine would have produced.
- **a NaN update never reaches the engine**: the in-jit guard reverts
  and the loop gates the publish on the ``notfinite`` flag.
- **the masked ragged objective**: prompt tokens and pad carry exactly
  zero gradient; the packed grouped-GEMM loss equals a dense reference.
"""
import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.models.lora import jit_merge, lora_bundle
from distributed_training_guide_tpu.post import (PostTrainingLoop,
                                                 ProgrammaticScorer,
                                                 Rollout, RolloutLedger,
                                                 TeacherScorer, band_reward,
                                                 generate_rollouts,
                                                 match_reward, merged_params,
                                                 pack_rollouts, rollout_seed)
from distributed_training_guide_tpu.serve.api import generate_many
from distributed_training_guide_tpu.serve.elastic import new_generation
from distributed_training_guide_tpu.serve.engine import (ModelPrograms,
                                                         ServeEngine)
from distributed_training_guide_tpu.serve.router import local_fleet
from distributed_training_guide_tpu.serve.scheduler import Request
from distributed_training_guide_tpu.train.optimizer import adamw_cosine
from distributed_training_guide_tpu.train.step import (POST_BASELINES,
                                                       POST_OBJECTIVES,
                                                       Trainer,
                                                       make_post_step,
                                                       post_loss)

pytestmark = [pytest.mark.serve, pytest.mark.post]


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def base():
    return get_model("llama-debug", dtype=jnp.float32)


@pytest.fixture(scope="module")
def p0(base):
    return base.init(base.config, jax.random.key(0))


@pytest.fixture(scope="module")
def engine0(base, p0):
    """READ-ONLY shared engine: always serves ``p0`` — publish/mutation
    tests use their own programs (``programs_mut``), never this one."""
    return ServeEngine(base, p0, n_slots=4, page_size=16, max_len=64)


@pytest.fixture(scope="module")
def programs_mut(base, p0):
    """The program cache the publish/elastic/router tests MUTATE — each
    test publishes whatever weights it needs first."""
    return ModelPrograms(base, p0)


def _audit(eng):
    """refcount == holders, free + held + cached == capacity (the
    repo-wide pool invariant, re-pinned per loop iteration here)."""
    sched, pool = eng.scheduler, eng.scheduler.pool
    held: dict = {}
    for slot in sched.slots:
        if slot is None:
            continue
        assert 0 not in slot.pages, "trash page in a live table"
        for p in slot.pages:
            held[p] = held.get(p, 0) + 1
    if sched.cache is not None:
        stack = [sched.cache.root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                held[child.page] = held.get(child.page, 0) + 1
                stack.append(child)
    for p, n in held.items():
        assert pool.refcount(p) == n, \
            f"page {p}: {n} holders, refcount {pool.refcount(p)}"
    assert pool.n_free + len(held) == pool.capacity, \
        (pool.n_free, len(held), pool.capacity)


def _auditing(engine):
    """Wrap ``engine.step`` so every scheduler iteration re-checks the
    pool invariants — the acceptance criterion's 'holding throughout'."""
    orig = engine.step

    def step():
        out = orig()
        _audit(engine)
        return out

    engine.step = step
    return engine


def _reqs(n=4, max_new=12, temp=0.7):
    return [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=max_new,
                    seed=100 + i, temperature=temp) for i in range(n)]


# ---------------------------------------------------------------------------
# units: seeds, ledger, packing
# ---------------------------------------------------------------------------

def test_rollout_seed_deterministic_and_distinct():
    assert rollout_seed(0, 3, 5) == rollout_seed(0, 3, 5)
    seeds = {rollout_seed(0, i, j) for i in range(20) for j in range(32)}
    assert len(seeds) == 20 * 32          # no collisions in a real batch
    assert rollout_seed(1, 3, 5) != rollout_seed(0, 3, 5)


def test_ledger_roundtrip_skips_torn_line(tmp_path):
    led = RolloutLedger(tmp_path / "led.jsonl")
    for idx in range(3):
        led.record(Rollout(iteration=2, index=idx, prompt_ids=[1, 2],
                           generated_ids=[4, 5, idx], seed=idx,
                           finish_reason="length"))
    with open(led.path, "a") as fp:
        fp.write('{"iteration": 2, "index": 99, "trunc')   # crash mid-write
    done = led.completed(2)
    assert sorted(done) == [0, 1, 2]      # torn line skipped, not fatal
    assert done[1].generated_ids == [4, 5, 1]
    assert led.completed(0) == {}
    assert led.last_iteration() == 2


def test_pack_rollouts_layout_and_validation():
    r = [Rollout(iteration=0, index=i, prompt_ids=[7, 8],
                 generated_ids=[10 + i] * (i + 1), seed=i,
                 finish_reason="length", group_id=i // 2) for i in range(3)]
    scores = [ProgrammaticScorer(lambda p, g: 0.5).score([x])[0] for x in r]
    batch = pack_rollouts(r, scores, pad_to=8)
    assert batch["tokens"].shape == (3, 8)
    assert batch["tokens"][2, :5].tolist() == [7, 8, 12, 12, 12]
    assert batch["tokens"][2, 5:].tolist() == [0, 0, 0]
    assert batch["prompt_lens"].tolist() == [2, 2, 2]
    assert batch["total_lens"].tolist() == [3, 4, 5]
    assert batch["group_ids"].tolist() == [0, 0, 1]
    with pytest.raises(ValueError, match="pad_to"):
        pack_rollouts(r, scores, pad_to=4)
    with pytest.raises(ValueError, match="vocab_size"):
        pack_rollouts(r, scores, pad_to=8, with_teacher=True)
    with pytest.raises(ValueError, match="teacher_logprobs"):
        pack_rollouts(r, scores, pad_to=8, with_teacher=True, vocab_size=32)


def test_pack_rollouts_teacher_rows_at_source_positions():
    r = [Rollout(iteration=0, index=0, prompt_ids=[7, 8, 9],
                 generated_ids=[1, 2], seed=0, finish_reason="length")]
    rows = np.arange(2 * 16, dtype=np.float32).reshape(2, 16)
    scores = [dataclasses.replace(
        ProgrammaticScorer(lambda p, g: 0.0).score(r)[0],
        teacher_logprobs=rows)]
    batch = pack_rollouts(r, scores, pad_to=8, vocab_size=16,
                          with_teacher=True)
    # source position pl-1+j predicts generated token j
    assert np.array_equal(batch["teacher_logprobs"][0, 2:4], rows)
    assert not batch["teacher_logprobs"][0, 4:].any()
    assert not batch["teacher_logprobs"][0, :2].any()


# ---------------------------------------------------------------------------
# the masked ragged objective
# ---------------------------------------------------------------------------

def _dense_reinforce(logits, tokens, pl, tl, adv):
    logp = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), -1)
    total = 0.0
    for b in range(tokens.shape[0]):
        for p in range(pl[b] - 1, tl[b] - 1):
            total += adv[b] * logp[b, p, tokens[b, p + 1]]
    return -total / tokens.shape[0]


def test_post_loss_reinforce_matches_dense_reference():
    rng = np.random.RandomState(0)
    b, s, v = 3, 12, 32
    logits = jnp.asarray(rng.randn(b, s, v), jnp.float32)
    tokens = jnp.asarray(rng.randint(0, v, (b, s)), jnp.int32)
    pl = jnp.asarray([3, 5, 2], jnp.int32)
    tl = jnp.asarray([9, 6, 12], jnp.int32)
    adv = jnp.asarray([0.5, -1.0, 2.0], jnp.float32)
    loss, extras = post_loss(logits, tokens, pl, tl, advantages=adv)
    ref = _dense_reinforce(np.asarray(logits), np.asarray(tokens),
                           np.asarray(pl), np.asarray(tl), np.asarray(adv))
    assert abs(float(loss) - float(ref)) < 1e-5
    assert float(extras["post_tokens"]) == float((tl - pl).sum())


@pytest.mark.parametrize("objective", POST_OBJECTIVES)
def test_post_loss_masks_prompt_and_pad_gradients(objective):
    """The masked-loss contract, pinned AT THE GRADIENT: only source
    positions of sampled continuation tokens (pl-1 .. tl-2) carry
    gradient; prompt rows and the pad tail are exactly zero."""
    rng = np.random.RandomState(1)
    b, s, v = 2, 10, 16
    logits = jnp.asarray(rng.randn(b, s, v), jnp.float32)
    tokens = jnp.asarray(rng.randint(0, v, (b, s)), jnp.int32)
    pl = jnp.asarray([3, 4], jnp.int32)
    tl = jnp.asarray([7, 10], jnp.int32)
    kw = (dict(advantages=jnp.asarray([1.0, -0.5]))
          if objective == "reinforce" else
          dict(teacher_logprobs=jax.nn.log_softmax(
              jnp.asarray(rng.randn(b, s, v), jnp.float32), -1)))
    grads = jax.grad(lambda lg: post_loss(
        lg, tokens, pl, tl, objective=objective, **kw)[0])(logits)
    grads = np.asarray(grads)
    for i in range(b):
        live = slice(int(pl[i]) - 1, int(tl[i]) - 1)
        assert np.abs(grads[i, live]).max() > 0
        dead = np.concatenate([grads[i, :int(pl[i]) - 1],
                               grads[i, int(tl[i]) - 1:]])
        assert not dead.any(), f"seq {i}: prompt/pad rows carry gradient"


def test_post_loss_validation():
    logits = jnp.zeros((1, 4, 8))
    tokens = jnp.zeros((1, 4), jnp.int32)
    pl = jnp.asarray([1], jnp.int32)
    tl = jnp.asarray([3], jnp.int32)
    with pytest.raises(ValueError, match="unknown post objective"):
        post_loss(logits, tokens, pl, tl, objective="ppo")
    with pytest.raises(ValueError, match="needs advantages"):
        post_loss(logits, tokens, pl, tl, objective="reinforce")
    with pytest.raises(ValueError, match="needs teacher_logprobs"):
        post_loss(logits, tokens, pl, tl, objective="distill_kl")


def test_make_post_step_validation(base):
    tr = Trainer(bundle=base, optimizer=adamw_cosine(1e-3))
    with pytest.raises(ValueError, match="unknown post objective"):
        make_post_step(tr, objective="dpo")
    with pytest.raises(ValueError, match="unknown post baseline"):
        make_post_step(tr, baseline="critic")
    assert "group" in POST_BASELINES     # the GRPO form stays spellable
    # a callable attn_impl must refuse, not silently swap to 'auto' —
    # the update would optimize a different model function than the one
    # generating the rollouts
    tr_callable = Trainer(bundle=base, optimizer=adamw_cosine(1e-3),
                          attn_impl=lambda *a, **k: None)
    with pytest.raises(ValueError, match="callable"):
        make_post_step(tr_callable)


def test_lora_only_requires_lora_bundle(base):
    with pytest.raises(ValueError, match="lora_bundle"):
        Trainer(bundle=base, optimizer=adamw_cosine(1e-3), lora_only=True)


def test_jit_merge_matches_base_layout(base, p0):
    wrapped = lora_bundle(base, rank=4, alpha=8.0)
    lp = wrapped.init(base.config, jax.random.key(1))
    merged = jit_merge(wrapped)(lp)
    assert (jax.tree_util.tree_structure(merged)
            == jax.tree_util.tree_structure(p0))
    for a, b in zip(jax.tree.leaves(merged), jax.tree.leaves(p0)):
        assert a.shape == b.shape and a.dtype == b.dtype
    with pytest.raises(ValueError, match="lora_bundle"):
        jit_merge(base)


# ---------------------------------------------------------------------------
# publish_params: layout validation + the retrace-free bitwise pin
# ---------------------------------------------------------------------------

def test_publish_params_validates_layout(base, p0, programs_mut):
    programs_mut.publish_params(p0)            # reset to known weights
    flat, treedef = jax.tree_util.tree_flatten(p0)

    bad_shape = jax.tree_util.tree_unflatten(
        treedef, [jnp.zeros((3, 3), jnp.float32) if i == 0 else leaf
                  for i, leaf in enumerate(flat)])
    with pytest.raises(ValueError, match="shape"):
        programs_mut.publish_params(bad_shape)

    bad_dtype = jax.tree_util.tree_unflatten(
        treedef, [leaf.astype(jnp.bfloat16) if i == 0 else leaf
                  for i, leaf in enumerate(flat)])
    with pytest.raises(ValueError, match="dtype"):
        programs_mut.publish_params(bad_dtype)

    with pytest.raises(ValueError, match="tree does not match"):
        programs_mut.publish_params({"wrong": flat[0]})

    # the error names the offending leaf so a stale-layout publish is
    # debuggable from the message alone
    try:
        programs_mut.publish_params(bad_shape)
    except ValueError as exc:
        leaf_name = jax.tree_util.keystr(
            jax.tree_util.tree_flatten_with_path(p0)[0][0][0])
        assert leaf_name in str(exc)


def test_publish_rejected_while_swap_in_flight(base, p0, programs_mut):
    with programs_mut.swap_guard():
        with pytest.raises(RuntimeError, match="swap"):
            programs_mut.publish_params(p0)
        with pytest.raises(RuntimeError, match="already in flight"):
            programs_mut.swap_guard().__enter__()
    programs_mut.publish_params(p0)            # released cleanly


def test_publish_refused_with_inflight_work(base, p0, programs_mut):
    eng = ServeEngine(base, p0, n_slots=2, page_size=16, max_len=64,
                      programs=programs_mut)
    eng.programs.publish_params(p0)
    eng.submit(Request(prompt_ids=[3, 17, 42], max_new_tokens=4))
    with pytest.raises(RuntimeError, match="in flight"):
        eng.publish_params(p0)
    eng.publish_params(p0, force=True)         # the caller's explicit out
    while eng.has_work:
        eng.step()
    eng.publish_params(p0)                     # drained: allowed


def test_publish_retrace_free_and_bitwise_vs_fresh_engine(base, p0,
                                                          programs_mut):
    """THE acceptance pin: a publish leaves every jit cache untouched,
    and decode-after-publish is bitwise a fresh engine built from the
    published params."""
    programs_mut.publish_params(p0)
    eng = ServeEngine(base, p0, n_slots=4, page_size=16, max_len=64,
                      programs=programs_mut)
    reqs = _reqs(4)
    before = [r.generated_ids for r in generate_many(
        eng, [dataclasses.replace(r, request_id=None) for r in reqs])]

    sizes0 = eng.programs.jit_cache_sizes()
    assert sizes0["decode"] >= 1
    p1 = jax.tree.map(lambda x: x * 1.05, p0)
    count = eng.publish_params(p1)
    assert count == eng.programs.publish_count

    after = [r.generated_ids for r in generate_many(
        eng, [dataclasses.replace(r, request_id=None) for r in reqs])]
    assert eng.programs.jit_cache_sizes() == sizes0, \
        "a weight publish retraced a program"
    assert after != before                    # the weights actually moved

    fresh = ServeEngine(base, p1, n_slots=4, page_size=16, max_len=64)
    ref = [r.generated_ids for r in generate_many(
        fresh, [dataclasses.replace(r, request_id=None) for r in reqs])]
    assert after == ref, \
        "decode-after-publish diverged from a fresh engine on the " \
        "published params"


# ---------------------------------------------------------------------------
# rollout reproducibility
# ---------------------------------------------------------------------------

PROMPTS = [[3 + i, 17, 42, 17, 42] for i in range(4)]


def test_rollouts_reproducible_across_engine_restart(base, p0, engine0):
    rolls_a, stats = generate_rollouts(
        engine0, PROMPTS, iteration=3, base_seed=11, max_new_tokens=10,
        temperature=0.8)
    assert stats["rollout_tokens"] == sum(
        len(r.generated_ids) for r in rolls_a)
    # a RESTARTED engine: fresh programs, fresh pool, same weights
    restarted = ServeEngine(base, p0, n_slots=4, page_size=16, max_len=64)
    rolls_b, _ = generate_rollouts(
        restarted, PROMPTS, iteration=3, base_seed=11, max_new_tokens=10,
        temperature=0.8)
    assert [r.generated_ids for r in rolls_a] \
        == [r.generated_ids for r in rolls_b]
    assert [r.seed for r in rolls_a] == [r.seed for r in rolls_b]
    # a different iteration derives different seeds -> different samples
    rolls_c, _ = generate_rollouts(
        engine0, PROMPTS, iteration=4, base_seed=11, max_new_tokens=10,
        temperature=0.8)
    assert [r.generated_ids for r in rolls_a] \
        != [r.generated_ids for r in rolls_c]


def test_rollouts_identical_spec_on_vs_off(base, p0, engine0):
    spec_eng = ServeEngine(base, p0, n_slots=4, page_size=16, max_len=64,
                          programs=engine0.programs, speculate="ngram",
                          spec_k=4)
    kw = dict(iteration=5, base_seed=7, max_new_tokens=12, temperature=0.7)
    plain, _ = generate_rollouts(engine0, PROMPTS, **kw)
    spec, _ = generate_rollouts(spec_eng, PROMPTS, **kw)
    assert [r.generated_ids for r in plain] \
        == [r.generated_ids for r in spec]


def test_chaos_engine_killed_mid_batch_resumes_from_ledger(
        base, p0, engine0, tmp_path):
    """The chaos drill: the engine dies mid-rollout-batch; a fresh
    engine + the same ledger finish the batch with no double-counting,
    bitwise identical to an uninterrupted run."""
    kw = dict(iteration=7, base_seed=3, max_new_tokens=8, temperature=0.9)
    golden, _ = generate_rollouts(engine0, PROMPTS, **kw)

    ledger = RolloutLedger(tmp_path / "rollouts.jsonl")
    doomed = ServeEngine(base, p0, n_slots=2, page_size=16, max_len=64,
                         programs=engine0.programs)
    orig = doomed.step
    calls = {"n": 0}

    def dying_step():
        if calls["n"] >= 12:                  # mid-batch, some recorded
            raise RuntimeError("engine killed")
        calls["n"] += 1
        return orig()

    doomed.step = dying_step
    with pytest.raises(RuntimeError, match="killed"):
        generate_rollouts(doomed, PROMPTS, ledger=ledger, **kw)
    recorded = ledger.completed(7)
    assert 0 < len(recorded) < len(PROMPTS), \
        "the drill must die MID-batch (tune the step budget)"

    # fresh incarnation, same ledger: only the missing samples generate
    revived = ServeEngine(base, p0, n_slots=2, page_size=16, max_len=64)
    rolls, stats = generate_rollouts(revived, PROMPTS, ledger=ledger, **kw)
    assert stats["resumed_from_ledger"] == len(recorded)
    assert [r.generated_ids for r in rolls] \
        == [r.generated_ids for r in golden]
    # throughput counts only the tokens THIS incarnation generated —
    # ledger-resumed samples at ~0 wall would otherwise report absurd
    # tok/s into any bench mean
    assert stats["rollout_tokens"] == sum(
        len(golden[i].generated_ids) for i in range(len(PROMPTS))
        if i not in recorded)
    # no double-counting: exactly one ledger line per (iteration, index)
    with open(ledger.path) as fp:
        keys = [(d["iteration"], d["index"]) for d in map(json.loads, fp)]
    assert sorted(keys) == sorted(set(keys))
    assert len(keys) == len(PROMPTS)


# ---------------------------------------------------------------------------
# the end-to-end loop (the acceptance criterion)
# ---------------------------------------------------------------------------

def test_e2e_reinforce_loop_reward_improves(base):
    """rollout → score → update → publish for 6 iterations on the dense
    synthetic preference task: (a) reward measurably improves, (b) the
    publish is retrace-free (jit caches flat), (c) decode-after-publish
    is bitwise a fresh engine on the final params, (d) pool invariants
    hold on every engine iteration throughout."""
    trainer = Trainer(bundle=base, optimizer=adamw_cosine(0.1),
                      guard_policy="skip")
    state = trainer.init_state(0)
    engine = _auditing(ServeEngine(base, merged_params(trainer, state),
                                   n_slots=8, page_size=16, max_len=64))
    prompts = [[3, 10, 17] for _ in range(24)]
    loop = PostTrainingLoop(
        trainer, engine, ProgrammaticScorer(band_reward(64)), prompts,
        state=state, max_new_tokens=16, temperature=1.0, base_seed=0)
    first = loop.run_iteration()
    sizes0 = engine.programs.jit_cache_sizes()   # everything warmed
    hist = loop.run(4)

    rewards = [first["reward_mean"]] + [m["reward_mean"] for m in hist]
    assert rewards[-1] > rewards[0] + 0.2, \
        f"reward did not improve: {rewards}"
    assert loop.publishes == 5
    assert engine.programs.jit_cache_sizes() == sizes0, \
        "a publish retraced a program mid-loop"
    assert all(m["publish_ms"] >= 0 and m["published"] for m in hist)
    assert all(np.isfinite(m["loss"]) for m in hist)

    # (c) the engine after 6 publishes IS a fresh engine on the params
    final = merged_params(trainer, loop.state)
    reqs = _reqs(3, max_new=8)
    got = [r.generated_ids for r in generate_many(
        engine, [dataclasses.replace(r, request_id=None) for r in reqs])]
    fresh = ServeEngine(base, final, n_slots=8, page_size=16, max_len=64)
    ref = [r.generated_ids for r in generate_many(
        fresh, [dataclasses.replace(r, request_id=None) for r in reqs])]
    assert got == ref


def test_e2e_distill_lora_loop(base):
    """The LoRA + distillation leg: adapter-only updates (base params
    bitwise frozen), merged publish through ONE compiled merge, and the
    KL objective actually descending on the student's own rollouts."""
    teacher_params = base.init(base.config, jax.random.key(7))
    bundle = lora_bundle(base, rank=8, alpha=16.0,
                         targets=("wq", "wv", "down"))
    trainer = Trainer(bundle=bundle, optimizer=adamw_cosine(0.1),
                      lora_only=True, guard_policy="skip")
    state = trainer.init_state(0)
    base_before = jax.tree.map(np.asarray, state.params["base"])
    engine = _auditing(ServeEngine(base, merged_params(trainer, state),
                                   n_slots=8, page_size=16, max_len=64))
    prompts = [[3 + (g * 7 + j) % 200 for j in range(3)] for g in range(12)]
    loop = PostTrainingLoop(
        trainer, engine, TeacherScorer(base, teacher_params), prompts,
        state=state, objective="distill_kl", max_new_tokens=10,
        temperature=1.0, base_seed=0)
    hist = loop.run(4)

    losses = [m["loss"] for m in hist]
    assert losses[-1] < losses[0], f"KL not descending: {losses}"
    assert loop.publishes == 4
    # lora_only: the masked optimizer zeroes every base update
    for a, b in zip(jax.tree.leaves(base_before),
                    jax.tree.leaves(loop.state.params["base"])):
        assert np.array_equal(a, np.asarray(b)), \
            "lora_only let a base parameter move"
    # and the adapters did move
    deltas = [float(jnp.abs(x).max())
              for x in jax.tree.leaves(loop.state.params["lora"])]
    assert max(deltas) > 0


def test_distill_objective_requires_teacher_scorer(base, p0, engine0):
    tr = Trainer(bundle=base, optimizer=adamw_cosine(1e-3))
    with pytest.raises(ValueError, match="TeacherScorer"):
        PostTrainingLoop(tr, engine0,
                         ProgrammaticScorer(match_reward(3)), PROMPTS,
                         state=tr.init_state(0), objective="distill_kl")


def test_nan_update_gates_publish(base, monkeypatch):
    """A NaN update must not poison the publishing engine: the in-jit
    guard reverts the state and the loop skips that publish — the engine
    keeps serving the last good policy."""
    monkeypatch.setenv("DTG_FAULT_NAN_LOSS_STEP", "1")
    trainer = Trainer(bundle=base, optimizer=adamw_cosine(0.05),
                      guard_policy="skip")
    state = trainer.init_state(0)
    engine = ServeEngine(base, merged_params(trainer, state),
                         n_slots=4, page_size=16, max_len=64)
    loop = PostTrainingLoop(
        trainer, engine, ProgrammaticScorer(band_reward(64)),
        [[3, 10, 17]] * 2, state=state, max_new_tokens=6,
        temperature=1.0, base_seed=0)
    m0 = loop.run_iteration()                 # step 0 -> fine, publishes
    count_before = engine.programs.publish_count
    m1 = loop.run_iteration()                 # step 1 -> NaN loss
    m2 = loop.run_iteration()                 # recovered
    assert m0["published"] and not m0["publish_skipped_nonfinite"]
    assert m1["publish_skipped_nonfinite"] and not m1["published"]
    assert engine.programs.publish_count == count_before + 1  # only m2's
    assert m2["published"] and np.isfinite(m2["loss"])
    assert loop.publishes_skipped == 1
    # the guard reverted: post-NaN params are finite end to end
    assert all(bool(jnp.isfinite(x).all())
               for x in jax.tree.leaves(loop.state.params))


# ---------------------------------------------------------------------------
# elastic + router: the published-params path
# ---------------------------------------------------------------------------

def test_new_generation_rejected_override_leaves_weights_unpublished(
        base, p0, programs_mut):
    """Validation failures must precede the publish: a rejected baked
    override (or a failed construction) leaves the old generation still
    serving the OLD weights — publishing first would hand its in-flight
    sequences new weights with no replay."""
    programs_mut.publish_params(p0)
    old = ServeEngine(base, p0, n_slots=2, page_size=16, max_len=64,
                      programs=programs_mut)
    count = programs_mut.publish_count
    p1 = jax.tree.map(lambda x: x * 1.01, p0)
    with pytest.raises(ValueError, match="kv_dtype"):
        new_generation(old, params=p1, kv_dtype="int8")
    assert programs_mut.publish_count == count, \
        "a rejected swap published anyway"


def test_new_generation_publishes_params(base, p0, programs_mut):
    """Weight-publish and capacity swap in ONE call: new_generation
    (params=) publishes into the shared programs — retrace-free — and
    the new generation decodes exactly like a fresh engine on the
    published weights."""
    programs_mut.publish_params(p0)
    old = ServeEngine(base, p0, n_slots=2, page_size=16, max_len=64,
                      programs=programs_mut)
    generate_many(old, [Request(prompt_ids=[3, 17, 42], max_new_tokens=4)])
    sizes0 = programs_mut.jit_cache_sizes()
    p1 = jax.tree.map(lambda x: x * 0.97, p0)
    count = programs_mut.publish_count
    new = new_generation(old, params=p1, n_slots=4)
    assert programs_mut.publish_count == count + 1
    assert programs_mut.jit_cache_sizes() == sizes0

    reqs = _reqs(2, max_new=8)
    got = [r.generated_ids for r in generate_many(
        new, [dataclasses.replace(r, request_id=None) for r in reqs])]
    fresh = ServeEngine(base, p1, n_slots=4, page_size=16, max_len=64)
    ref = [r.generated_ids for r in generate_many(
        fresh, [dataclasses.replace(r, request_id=None) for r in reqs])]
    assert got == ref


def test_swap_with_params_replays_under_new_weights(
        base, p0, programs_mut):
    """A swap that also publishes forces the replay seat — pinned on
    the TWO-CALL form (new_generation + swap_generation, no explicit
    force flag): the published-params stamp must make the seat replay
    on its own. Every carried sequence keeps its already-emitted tokens
    VERBATIM (replay), then continues under the published weights; pool
    invariants hold on the new generation."""
    from distributed_training_guide_tpu.serve.elastic import \
        swap_generation

    programs_mut.publish_params(p0)
    old = ServeEngine(base, p0, n_slots=4, page_size=16, max_len=64,
                      programs=programs_mut)
    reqs = _reqs(4, max_new=16)
    ids = [old.submit(dataclasses.replace(r, request_id=None))
           for r in reqs]
    done: dict = {}
    for _ in range(6):                        # emit some tokens pre-swap
        for res in old.step():
            done[res.request_id] = res
    pre = {rid: list(toks) for rid, toks in old.partial_tokens().items()}
    assert any(pre.values())

    p1 = jax.tree.map(lambda x: x * 1.03, p0)
    new = new_generation(old, params=p1, n_slots=4)
    # the publish already landed: stepping the OLD engine before the
    # swap would decode old-policy k/v under the new weights — refused
    with pytest.raises(RuntimeError, match="swap"):
        old.step()
    evicted, stats = swap_generation(old, new)
    assert not evicted
    assert stats["seated"] == 0               # payload seat disabled:
    assert stats["requeued"] > 0              # old-policy k/v not reused
    new = _auditing(new)
    while new.has_work:
        for res in new.step():
            done[res.request_id] = res
    for rid in ids:
        if rid in pre and pre[rid]:
            assert done[rid].generated_ids[:len(pre[rid])] == pre[rid], \
                "a replayed sequence rewrote its emitted tokens"
    # old generation drained empty
    assert old.scheduler.pool.n_free == old.scheduler.pool.capacity


def test_disagg_publish_updates_both_engines_atomically(base, p0):
    """The disagg pair shares ONE ModelPrograms — a publish updates the
    prefill and decode sides together, with the same in-flight refusal."""
    from distributed_training_guide_tpu.serve.disagg import DisaggEngine

    eng = DisaggEngine(base, p0, n_slots=2, page_size=16, max_len=64)
    eng.submit(Request(prompt_ids=[3, 17, 42], max_new_tokens=4))
    with pytest.raises(RuntimeError, match="in-flight"):
        eng.publish_params(p0)
    while eng.has_work:
        eng.step()
    p1 = jax.tree.map(lambda x: x * 1.04, p0)
    eng.publish_params(p1)
    assert eng.prefill.programs is eng.decode.programs is eng.programs
    got = [r.generated_ids for r in generate_many(
        eng, [dataclasses.replace(r, request_id=None)
              for r in _reqs(2, max_new=6)])]
    fresh = ServeEngine(base, p1, n_slots=2, page_size=16, max_len=64)
    ref = [r.generated_ids for r in generate_many(
        fresh, [dataclasses.replace(r, request_id=None)
                for r in _reqs(2, max_new=6)])]
    assert got == ref


def test_model_scorer_tracks_published_params(base, p0, programs_mut):
    """A scorer pointed at a live engine's programs scores with the
    CURRENT weights — a publish must not leave it scoring (and pinning
    in memory) the superseded policy."""
    from distributed_training_guide_tpu.post import RewardModelScorer

    programs_mut.publish_params(p0)
    rolls = [Rollout(iteration=0, index=0, prompt_ids=[3, 17, 42],
                     generated_ids=[5, 9, 11], seed=0,
                     finish_reason="length")]
    live = RewardModelScorer(programs_mut)
    before = live.score(rolls)[0].reward
    p1 = jax.tree.map(lambda x: x * 1.1, p0)
    programs_mut.publish_params(p1)
    after = live.score(rolls)[0].reward
    assert after != before
    static = RewardModelScorer(base, p1)
    assert abs(after - static.score(rolls)[0].reward) < 1e-6


def test_loop_run_zero_iterations_returns_empty(base, p0, engine0):
    tr = Trainer(bundle=base, optimizer=adamw_cosine(1e-3))
    loop = PostTrainingLoop(tr, engine0,
                            ProgrammaticScorer(band_reward(8)), PROMPTS,
                            state=tr.init_state(0), frozen=True)
    loop.history = [{"stale": True}]         # prior history must not leak
    assert loop.run(0) == []


def test_disagg_swap_with_params_publishes(base, p0):
    """The disagg branch of new_generation must publish too — a fleet
    of disagg replicas swapping with params= previously built the new
    pair and SKIPPED the publish (old policy kept serving while the
    loop believed the update landed)."""
    from distributed_training_guide_tpu.serve.disagg import DisaggEngine
    from distributed_training_guide_tpu.serve.elastic import \
        swap_generation

    eng = DisaggEngine(base, p0, n_slots=2, page_size=16, max_len=64)
    generate_many(eng, [Request(prompt_ids=[3, 17, 42], max_new_tokens=2)])
    p1 = jax.tree.map(lambda x: x * 1.06, p0)
    count = eng.programs.publish_count
    new = new_generation(eng, params=p1, n_slots=2)
    assert eng.programs.publish_count == count + 1
    with pytest.raises(RuntimeError, match="swap"):
        eng.step()
    evicted, _ = swap_generation(eng, new)
    assert not evicted
    got = [r.generated_ids for r in generate_many(
        new, [dataclasses.replace(r, request_id=None)
              for r in _reqs(2, max_new=6)])]
    fresh = ServeEngine(base, p1, n_slots=2, page_size=16, max_len=64)
    ref = [r.generated_ids for r in generate_many(
        fresh, [dataclasses.replace(r, request_id=None)
                for r in _reqs(2, max_new=6)])]
    assert got == ref


def test_group_baseline_requires_real_groups(base, p0, engine0):
    """baseline='group' with singleton groups (the default
    group_id=index) is all-zero advantages — the loop must refuse, not
    train nothing while looking busy."""
    tr = Trainer(bundle=base, optimizer=adamw_cosine(1e-3))
    with pytest.raises(ValueError, match="group"):
        PostTrainingLoop(tr, engine0,
                         ProgrammaticScorer(band_reward(8)), PROMPTS,
                         state=tr.init_state(0), baseline="group")
    with pytest.raises(ValueError, match="group"):
        PostTrainingLoop(tr, engine0,
                         ProgrammaticScorer(band_reward(8)), PROMPTS,
                         state=tr.init_state(0), baseline="group",
                         group_ids=list(range(len(PROMPTS))))


def test_skipped_boundary_publish_stays_due(base, monkeypatch):
    """publish_every > 1: a NaN landing ON the publish boundary must
    not double the staleness window — the publish stays due and the
    next finite step delivers it."""
    monkeypatch.setenv("DTG_FAULT_NAN_LOSS_STEP", "1")
    trainer = Trainer(bundle=base, optimizer=adamw_cosine(0.05),
                      guard_policy="skip")
    state = trainer.init_state(0)
    engine = ServeEngine(base, merged_params(trainer, state),
                         n_slots=4, page_size=16, max_len=64)
    loop = PostTrainingLoop(
        trainer, engine, ProgrammaticScorer(band_reward(64)),
        [[3, 10, 17]] * 2, state=state, max_new_tokens=6,
        temperature=1.0, base_seed=0, publish_every=2)
    m0 = loop.run_iteration()                 # not a boundary: no publish
    m1 = loop.run_iteration()                 # boundary + NaN: due, skipped
    m2 = loop.run_iteration()                 # off-boundary: delivers it
    assert not m0["published"] and not m0["publish_skipped_nonfinite"]
    assert m1["publish_skipped_nonfinite"] and not m1["published"]
    assert m2["published"]
    assert loop.publishes == 1 and loop.publishes_skipped == 1


def test_router_fleet_publish_and_swap(base, p0):
    fleet = local_fleet(base, p0, 2, n_slots=2, page_size=16, max_len=64)
    p1 = jax.tree.map(lambda x: x * 1.02, p0)
    # all-or-nothing: one busy replica refuses the WHOLE publish before
    # any cache mutates (a partial publish = fleet on mixed weights =
    # fence-recovery replays under different params)
    busy = next(iter(fleet.replicas.values()))
    busy.engine.submit(Request(prompt_ids=[3, 17, 42], max_new_tokens=2))
    count0 = busy.engine.programs.publish_count
    with pytest.raises(RuntimeError, match="mixed weights"):
        fleet.publish_params(p1)
    assert busy.engine.programs.publish_count == count0
    assert fleet.counters["param_publishes"] == 0
    while busy.engine.has_work:
        busy.engine.step()
    # shared programs -> ONE cache updated, counted once
    assert fleet.publish_params(p1) == 1
    assert fleet.counters["param_publishes"] == 1
    reqs = _reqs(2, max_new=6)
    got = [r.generated_ids for r in generate_many(
        fleet, [dataclasses.replace(r, request_id=None) for r in reqs])]
    fresh = ServeEngine(base, p1, n_slots=2, page_size=16, max_len=64)
    ref = [r.generated_ids for r in generate_many(
        fresh, [dataclasses.replace(r, request_id=None) for r in reqs])]
    assert got == ref, "fleet decode-after-publish diverged"

    # publish-and-resize through swap_replica rides the same seam
    name = next(iter(fleet.replicas))
    p2 = jax.tree.map(lambda x: x * 0.99, p1)
    fleet.swap_replica(name, params=p2, n_slots=4)
    assert fleet.counters["generation_swaps"] == 1
    assert fleet.counters["param_publishes"] == 2
    with pytest.raises(ValueError, match="no replica"):
        fleet.publish_params(p1, name="ghost")


# ---------------------------------------------------------------------------
# preflight colocation pricing + engine config + CLI
# ---------------------------------------------------------------------------

def test_price_post_colocation_and_budget_refusal(base):
    from distributed_training_guide_tpu.train.preflight import \
        price_post_colocation

    full = Trainer(bundle=base, optimizer=adamw_cosine(1e-3))
    lora = Trainer(bundle=lora_bundle(base, rank=4),
                   optimizer=adamw_cosine(1e-3), lora_only=True)
    rf = price_post_colocation(full, n_slots=4, max_len=64)
    rl = price_post_colocation(lora, n_slots=4, max_len=64)
    for key in ("policy_param_bytes", "policy_opt_state_bytes",
                "engine_param_bytes", "engine_pool_bytes", "total_bytes"):
        assert rf[key] > 0
    # the LoRA promise, priced: adapter-only moments are far smaller
    assert rl["policy_opt_state_bytes"] < rf["policy_opt_state_bytes"] / 10
    assert rl["lora_only"] and not rf["lora_only"]
    # an impossible colocation refuses BEFORE any compile
    with pytest.raises(ValueError, match="budget"):
        price_post_colocation(full, n_slots=4, max_len=64, budget_bytes=1)
    ok = price_post_colocation(full, n_slots=4, max_len=64,
                               budget_bytes=rf["total_bytes"] + 1)
    assert ok["total_bytes"] == rf["total_bytes"]


def test_training_engine_lora_config(base):
    from distributed_training_guide_tpu.train.engine import TrainingEngine

    eng = TrainingEngine({"model": "llama-debug",
                          "lora": {"rank": 4, "alpha": 8.0,
                                   "targets": ["wq", "wv"]}})
    assert eng.trainer.lora_only
    assert getattr(eng.trainer.bundle, "lora_base", None) is not None


def test_post_cli_smoke(tmp_path, capsys):
    from distributed_training_guide_tpu.post.cli import main

    rc = main(["--iterations", "1", "--rollout-batch", "2",
               "--max-new-tokens", "4", "--prompt-len", "3",
               "--lora-rank", "0", "--lr", "0.05", "--n-slots", "2",
               "--ledger", str(tmp_path / "led.jsonl")])
    assert rc == 0
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    assert lines[0]["colocation_total_bytes"] > 0
    assert len(lines) == 2                     # header + 1 iteration
    for m in lines[1:]:
        assert m["published"] and np.isfinite(m["loss"])
        assert m["rollout_tokens"] > 0


def test_post_cli_budget_refusal():
    from distributed_training_guide_tpu.post.cli import main

    with pytest.raises(ValueError, match="budget"):
        main(["--iterations", "1", "--memory-budget-gb", "0.000001"])
    # non-divisible grouping refuses up front instead of silently
    # shrinking the rollout batch
    with pytest.raises(SystemExit, match="divisible"):
        main(["--rollout-batch", "8", "--group-size", "3"])
    # GRPO with singleton groups = all-zero advantages = trains nothing
    with pytest.raises(SystemExit, match="group-size"):
        main(["--baseline", "group"])


# ---------------------------------------------------------------------------
# >= 2-device grid (slow per the tier-1 budget policy)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_publish_into_tp_sharded_engine(base, p0, eight_devices):
    """The sharded publish path: params placed by the plan's shardings,
    published leaves land on the SAME shardings (device_put conform) —
    decode-after-publish bitwise a fresh sharded engine."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    plan = make_plan("tp", make_mesh(tp=2, devices=eight_devices[:2]))
    eng = ServeEngine(base, p0, n_slots=2, page_size=16, max_len=64,
                      plan=plan)
    reqs = _reqs(2, max_new=6)
    generate_many(eng, [dataclasses.replace(r, request_id=None)
                        for r in reqs])
    sizes0 = eng.programs.jit_cache_sizes()
    p1 = jax.tree.map(lambda x: x * 1.01, p0)
    eng.publish_params(p1)
    got = [r.generated_ids for r in generate_many(
        eng, [dataclasses.replace(r, request_id=None) for r in reqs])]
    assert eng.programs.jit_cache_sizes() == sizes0
    for leaf in jax.tree.leaves(eng.programs.params):
        assert len(leaf.sharding.device_set) in (1, 2)
    fresh = ServeEngine(base, p1, n_slots=2, page_size=16, max_len=64,
                        plan=plan)
    ref = [r.generated_ids for r in generate_many(
        fresh, [dataclasses.replace(r, request_id=None) for r in reqs])]
    assert got == ref
