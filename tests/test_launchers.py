"""Unit tests for the process-runtime layer: the gang launcher's fail-fast /
cleanup semantics and the supervisor's restart + hang-detection loop, driven
with plain subprocesses (no jax, fast). The full-integration versions — real
jax.distributed gangs through the chapter CLIs — live in
test_multiprocess.py; these pin the launcher mechanics themselves, including
paths the integration tests can't reach (launcher crash mid-spawn, heartbeat
kill).
"""
import json
import os
import subprocess
import sys
import time

import pytest

from distributed_training_guide_tpu.launch.local import launch_gang
from distributed_training_guide_tpu.launch.supervisor import run_supervised

PY = sys.executable


def test_gang_all_ranks_zero_exit():
    rc = launch_gang([PY, "-c", "import os; exit(0)"], nproc=3)
    assert rc == 0


def test_gang_failfast_terminates_survivors(tmp_path):
    """Rank 1 exits 7 immediately; rank 0 would sleep for 60 s — the gang
    must come down with rc 7 in seconds, not minutes."""
    marker = tmp_path / "r0_alive"
    cmd = [PY, "-c", (
        "import os, sys, time, pathlib\n"
        f"marker = pathlib.Path({str(marker)!r})\n"
        "if os.environ['RANK'] == '1':\n"
        "    while not marker.exists():\n"   # rank 0 provably started first
        "        time.sleep(0.05)\n"
        "    sys.exit(7)\n"
        "marker.write_text(os.environ['MASTER_PORT'])\n"
        "time.sleep(60)\n")]
    t0 = time.time()
    rc = launch_gang(cmd, nproc=2, poll_interval=0.05)
    assert rc == 7
    assert time.time() - t0 < 30          # nowhere near rank 0's sleep
    assert marker.exists()                # rank 0 really had started


def test_gang_env_contract_and_per_rank_error_files(tmp_path):
    """Every rank sees MASTER_ADDR/PORT + WORLD_SIZE + its RANK, and an
    inherited ERROR_FILE is suffixed per rank (torchelastic convention)."""
    out = tmp_path / "env"
    out.mkdir()
    cmd = [PY, "-c", (
        "import os, pathlib\n"
        f"d = pathlib.Path({str(out)!r})\n"
        "(d / os.environ['RANK']).write_text(\n"
        "    ','.join([os.environ['MASTER_ADDR'], os.environ['MASTER_PORT'],\n"
        "              os.environ['WORLD_SIZE'], os.environ['ERROR_FILE']]))\n")]
    rc = launch_gang(cmd, nproc=2,
                     env_extra={"ERROR_FILE": str(tmp_path / "err.json")})
    assert rc == 0
    r0 = (out / "0").read_text().split(",")
    r1 = (out / "1").read_text().split(",")
    assert r0[0] == "127.0.0.1" and r0[:3] == r1[:3]   # same rendezvous
    assert r0[3].endswith("err.json.rank0") and r1[3].endswith("err.json.rank1")


def test_gang_cleans_up_when_launcher_itself_fails(tmp_path):
    """A spawn failure mid-gang must not orphan already-started ranks
    blocked waiting for peers (the finally-path _terminate_survivors)."""
    import uuid

    token = f"GANG_ORPHAN_TEST_{uuid.uuid4().hex}"
    cmd = [PY, "-c", f"import time\n{token!r}\ntime.sleep(60)\n"]
    # rank1.out pre-created as a DIRECTORY: rank 0 (stdout=None) spawns
    # fine, then rank 1's log open("ab") raises IsADirectoryError — a spawn
    # failure strictly after a rank is already running
    log_dir = tmp_path / "logs"
    (log_dir / "rank1.out").mkdir(parents=True)
    with pytest.raises(OSError):
        launch_gang(cmd, nproc=2, log_dir=str(log_dir))
    # no process carrying the token may survive the finally-path cleanup
    deadline = time.time() + 15
    while time.time() < deadline:
        alive = subprocess.run(["pgrep", "-f", token],
                               capture_output=True).returncode == 0
        if not alive:
            return
        time.sleep(0.2)
    subprocess.run(["pkill", "-9", "-f", token])
    pytest.fail("rank 0 orphaned after launcher failure")


def test_supervisor_restarts_then_succeeds(tmp_path):
    """Exit 3 on the first attempt (no sentinel), 0 on the second —
    run_supervised must restart once and return 0, keeping per-attempt
    logs and the ERROR_FILE env contract."""
    sentinel = tmp_path / "ran_once"
    cmd = [PY, "-c", (
        "import os, pathlib, sys\n"
        f"s = pathlib.Path({str(sentinel)!r})\n"
        "print('attempt with ERROR_FILE', os.environ['ERROR_FILE'], flush=True)\n"
        "if s.exists():\n"
        "    sys.exit(0)\n"
        "s.write_text('x')\n"
        "sys.exit(3)\n")]
    rc = run_supervised(cmd, max_restarts=2, log_dir=tmp_path / "logs",
                        restart_backoff=0.05)
    assert rc == 0
    out0 = (tmp_path / "logs" / "attempt_0" / "stdout.log").read_text()
    out1 = (tmp_path / "logs" / "attempt_1" / "stdout.log").read_text()
    assert "attempt_0" in out0 and "attempt_1" in out1   # per-attempt files


def test_supervisor_exhausts_restarts(tmp_path):
    rc = run_supervised([PY, "-c", "import sys; sys.exit(5)"],
                        max_restarts=1, log_dir=tmp_path / "logs",
                        restart_backoff=0.05)
    assert rc == 5
    assert (tmp_path / "logs" / "attempt_1").is_dir()   # restarted once


def test_supervisor_exponential_backoff(tmp_path):
    """Two restarts with base 0.4s must sleep ~0.4 + ~0.8s between attempts
    — the crash loop is rate-limited (and the schedule doubles, not flat)."""
    t0 = time.time()
    rc = run_supervised([PY, "-c", "import sys; sys.exit(9)"],
                        max_restarts=2, log_dir=tmp_path / "logs",
                        restart_backoff=0.4)
    elapsed = time.time() - t0
    assert rc == 9
    assert (tmp_path / "logs" / "attempt_2").is_dir()
    assert elapsed >= 1.2                  # 0.4 + 0.8 backoff floors
    assert elapsed < 60


def test_supervisor_backoff_cap(tmp_path):
    """backoff_cap bounds the schedule: base 10 with cap 0.1 must not sleep
    anywhere near 10s."""
    t0 = time.time()
    run_supervised([PY, "-c", "import sys; sys.exit(9)"],
                   max_restarts=1, log_dir=tmp_path / "logs",
                   restart_backoff=10.0, backoff_cap=0.1)
    assert time.time() - t0 < 8


def _error_writing_worker(error: str) -> list:
    """A worker that writes its own torchelastic-style error file (what
    @record does) and exits nonzero."""
    return [PY, "-c", (
        "import json, os, sys\n"
        "path = os.environ['ERROR_FILE']\n"
        "os.makedirs(os.path.dirname(path) or '.', exist_ok=True)\n"
        "with open(path, 'w') as fp:\n"
        f"    json.dump({{'message': {{'error': {error!r},\n"
        "               'traceback': '...'}}, fp)\n"
        "sys.exit(1)\n")]


def test_supervisor_stops_on_poison_pill(tmp_path):
    """An OOM error file is a deterministic failure: the supervisor must
    stop after attempt 0 instead of burning its restart budget."""
    cmd = _error_writing_worker(
        "XlaRuntimeError('RESOURCE_EXHAUSTED: Out of memory allocating 1TB')")
    rc = run_supervised(cmd, max_restarts=3, log_dir=tmp_path / "logs",
                        restart_backoff=0.05)
    assert rc == 1
    assert (tmp_path / "logs" / "attempt_0").is_dir()
    assert not (tmp_path / "logs" / "attempt_1").exists()   # no restart


def test_supervisor_poison_in_rank_file_and_override(tmp_path):
    """Gangs write per-rank error files (error.json.rankN) — classification
    must read those too; --restart-on-poison opts back into restarting."""
    worker = [PY, "-c", (
        "import json, os, sys\n"
        "path = os.environ['ERROR_FILE'] + '.rank1'\n"
        "os.makedirs(os.path.dirname(path) or '.', exist_ok=True)\n"
        "with open(path, 'w') as fp:\n"
        "    json.dump({'message': {'error': \"ValueError('8 devices not "
        "divisible by tensor x pipeline = 3')\"}}, fp)\n"
        "sys.exit(1)\n")]
    rc = run_supervised(worker, max_restarts=2, log_dir=tmp_path / "a",
                        restart_backoff=0.05)
    assert rc == 1
    assert not (tmp_path / "a" / "attempt_1").exists()

    rc = run_supervised(worker, max_restarts=1, log_dir=tmp_path / "b",
                        restart_backoff=0.05, stop_on_poison=False)
    assert (tmp_path / "b" / "attempt_1").is_dir()          # blind restart


def test_supervisor_poison_in_foreign_error_file_shape(tmp_path):
    """A worker that writes {"message": "<plain string>"} (not our nested
    dict) must still classify — and the supervisor must report it without
    crashing on the foreign shape."""
    worker = [PY, "-c", (
        "import json, os, sys\n"
        "with open(os.environ['ERROR_FILE'], 'w') as fp:\n"
        "    json.dump({'message': 'RESOURCE_EXHAUSTED: out of memory'}, fp)\n"
        "sys.exit(1)\n")]
    rc = run_supervised(worker, max_restarts=2, log_dir=tmp_path / "logs",
                        restart_backoff=0.05)
    assert rc == 1
    assert not (tmp_path / "logs" / "attempt_1").exists()   # stopped cleanly


def test_supervisor_ignores_stale_preset_error_file(tmp_path, monkeypatch):
    """An operator-preset $ERROR_FILE left over from a PREVIOUS incarnation
    (poison payload already on disk before launch) must not classify: the
    supervisor unlinks it before starting the worker, and mtime-fences any
    survivor against the launch time — a crashing-but-transient worker
    keeps its restart budget."""
    stale = tmp_path / "err.json"
    stale.write_text(json.dumps({"message": {
        "error": "XlaRuntimeError('RESOURCE_EXHAUSTED: OOM from last week')"}}))
    monkeypatch.setenv("ERROR_FILE", str(stale))
    # worker fails WITHOUT writing an error file -> with the stale file
    # fenced there is no poison verdict, so the supervisor must restart
    rc = run_supervised([PY, "-c", "import sys; sys.exit(1)"],
                        max_restarts=1, log_dir=tmp_path / "logs",
                        restart_backoff=0.05)
    assert rc == 1
    assert (tmp_path / "logs" / "attempt_1").is_dir()   # restart happened
    assert not stale.exists()                           # fence unlinked it


def test_supervisor_mtime_fence_without_unlink(tmp_path):
    """The mtime fence alone (unlink defeated) must also ignore a stale
    payload: backdate a poison error file past the launch slack and check
    classification skips it."""
    from distributed_training_guide_tpu.launch.supervisor import _poison_reason

    err = tmp_path / "error.json"
    err.write_text(json.dumps({"message": {
        "error": "RESOURCE_EXHAUSTED: out of memory"}}))
    old = time.time() - 3600
    os.utime(err, (old, old))
    assert _poison_reason(err, launched_at=time.time()) is None
    assert _poison_reason(err, launched_at=old - 10) is not None


def test_supervisor_transient_error_file_still_restarts(tmp_path):
    """A non-poison error file (transient infra failure) must not disable
    elasticity."""
    cmd = _error_writing_worker("ConnectionError('coordinator unreachable')")
    rc = run_supervised(cmd, max_restarts=1, log_dir=tmp_path / "logs",
                        restart_backoff=0.05)
    assert rc == 1
    assert (tmp_path / "logs" / "attempt_1").is_dir()       # restarted


def test_supervisor_heartbeat_file_preferred_over_log_silence(tmp_path):
    """A worker that logs NOTHING but beats its HEARTBEAT_FILE must survive
    a heartbeat_timeout shorter than its runtime — under the old log-size
    heuristic this healthy-but-quiet worker was killed as hung."""
    cmd = [PY, "-c", (
        "import json, os, time\n"
        "path = os.environ['HEARTBEAT_FILE']\n"
        "for step in range(8):\n"
        "    with open(path + '.tmp', 'w') as fp:\n"
        "        json.dump({'step': step, 'time': time.time()}, fp)\n"
        "    os.replace(path + '.tmp', path)\n"
        "    time.sleep(0.4)\n")]
    rc = run_supervised(cmd, max_restarts=0, log_dir=tmp_path / "logs",
                        heartbeat_timeout=1.5)
    assert rc == 0                       # ~3.2s silent runtime, not killed


def test_supervisor_stale_heartbeat_kills_worker(tmp_path):
    """The inverse: a worker that beats once and then wedges (while still
    CHATTING on stdout — the chatty-death-spiral case the log heuristic
    misses) is killed when the heartbeat goes stale."""
    cmd = [PY, "-c", (
        "import json, os, time\n"
        "path = os.environ['HEARTBEAT_FILE']\n"
        "with open(path, 'w') as fp:\n"
        "    json.dump({'step': 1, 'time': time.time()}, fp)\n"
        "while True:\n"
        "    print('still chatting', flush=True)\n"
        "    time.sleep(0.2)\n")]
    t0 = time.time()
    rc = run_supervised(cmd, max_restarts=0, log_dir=tmp_path / "logs",
                        heartbeat_timeout=1.5)
    assert rc != 0
    assert time.time() - t0 < 60


def test_supervisor_heartbeat_kills_hung_worker(tmp_path):
    """A worker that stops producing output gets SIGKILLed after the
    heartbeat timeout — the collective-stall case where the process never
    exits (diagnosing-errors/README.md power-draw heuristic, in process
    form)."""
    cmd = [PY, "-c", (
        "import time\n"
        "print('alive', flush=True)\n"
        "time.sleep(600)\n")]
    t0 = time.time()
    rc = run_supervised(cmd, max_restarts=0, log_dir=tmp_path / "logs",
                        heartbeat_timeout=2.0)
    assert rc != 0
    assert time.time() - t0 < 120         # killed by heartbeat, not 600s
