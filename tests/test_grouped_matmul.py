"""Grouped (ragged) GEMM coverage: every impl vs a dense per-row reference,
Pallas (interpret) vs XLA-fallback parity, gradients, and the zero-tail
contract the EP dispatch relies on."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.ops.grouped_matmul import grouped_matmul

pytestmark = pytest.mark.grouped

# (m, k, n, sizes) — ragged group shapes incl. empty experts, a group
# spanning everything, tile-unaligned dims, and a garbage tail (sum < m)
SHAPES = [
    (16, 8, 12, [3, 0, 9, 4]),
    (64, 16, 24, [10, 0, 0, 30, 24]),
    (32, 8, 8, [0, 0, 0]),
    (40, 8, 8, [5, 5, 5, 5]),          # sum < m: tail rows must be zero
    (33, 7, 9, [33, 0, 0, 0, 0, 0]),   # one group takes all, odd dims
    (24, 8, 8, [1, 1, 1, 21]),
]


def _reference(lhs, rhs, sizes):
    seg = np.repeat(np.arange(len(sizes)), sizes)
    out = np.zeros((lhs.shape[0], rhs.shape[2]), np.float32)
    for i, s in enumerate(seg):
        out[i] = lhs[i] @ rhs[s]
    return out


def _inputs(m, k, n, g, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(m, k), jnp.float32),
            jnp.asarray(rng.randn(g, k, n), jnp.float32))


@pytest.mark.parametrize("impl", ["scan", "einsum", "ragged", "pallas"])
@pytest.mark.parametrize("m,k,n,sizes", SHAPES)
def test_matches_dense_reference(impl, m, k, n, sizes):
    lhs, rhs = _inputs(m, k, n, len(sizes))
    sz = jnp.asarray(sizes, jnp.int32)
    out = jax.jit(lambda l, r, s: grouped_matmul(
        l, r, s, impl=impl, block_rows=8, block_cols=8))(lhs, rhs, sz)
    np.testing.assert_allclose(np.asarray(out),
                               _reference(np.asarray(lhs), np.asarray(rhs),
                                          sizes), rtol=1e-5, atol=1e-5)


def test_tail_rows_are_zero_with_zero_grad():
    """Rows past sum(group_sizes) produce zeros AND zero gradient — the
    contract the expert-parallel local-slice window depends on (its static
    worst-case buffer carries a garbage tail)."""
    lhs, rhs = _inputs(40, 8, 8, 4, seed=3)
    sz = jnp.asarray([5, 5, 5, 5], jnp.int32)  # total 20 of 40 rows
    for impl in ("scan", "einsum", "ragged", "pallas"):
        out = grouped_matmul(lhs, rhs, sz, impl=impl, block_rows=8,
                             block_cols=8)
        assert bool(jnp.all(out[20:] == 0)), impl
        g = jax.grad(
            lambda l: jnp.sum(grouped_matmul(l, rhs, sz, impl=impl,
                                             block_rows=8, block_cols=8)**2)
        )(lhs)
        assert bool(jnp.all(g[20:] == 0)), impl


@pytest.mark.parametrize("m,k,n,sizes", SHAPES[:4])
def test_pallas_grads_match_fallback(m, k, n, sizes):
    """The Pallas custom_vjp (gmm for d_lhs, tgmm for d_rhs) against plain
    autodiff through the einsum fallback, on the interpret path (the same
    kernels compile on TPU)."""
    lhs, rhs = _inputs(m, k, n, len(sizes), seed=1)
    sz = jnp.asarray(sizes, jnp.int32)

    def loss(impl):
        return jax.jit(jax.grad(
            lambda l, r: jnp.sum(grouped_matmul(l, r, sz, impl=impl,
                                                block_rows=8,
                                                block_cols=8)**2),
            argnums=(0, 1)))(lhs, rhs)

    ref_dl, ref_dr = loss("einsum")
    pal_dl, pal_dr = loss("pallas")
    np.testing.assert_allclose(np.asarray(pal_dl), np.asarray(ref_dl),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(pal_dr), np.asarray(ref_dr),
                               rtol=1e-4, atol=1e-4)


def test_bf16_inputs_and_out_dtype():
    lhs, rhs = _inputs(24, 8, 8, 4, seed=2)
    sz = jnp.asarray([6, 6, 6, 6], jnp.int32)
    ref = _reference(np.asarray(lhs), np.asarray(rhs), [6, 6, 6, 6])
    for impl in ("scan", "einsum", "ragged", "pallas"):
        out = grouped_matmul(lhs.astype(jnp.bfloat16),
                             rhs.astype(jnp.bfloat16), sz, impl=impl,
                             block_rows=8, block_cols=8)
        assert out.dtype == jnp.bfloat16, impl
        np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                                   rtol=5e-2, atol=5e-2)
        f32 = grouped_matmul(lhs.astype(jnp.bfloat16),
                             rhs.astype(jnp.bfloat16), sz, impl=impl,
                             block_rows=8, block_cols=8,
                             preferred_element_type=jnp.float32)
        assert f32.dtype == jnp.float32, impl


def test_shape_and_impl_validation():
    lhs, rhs = _inputs(16, 8, 8, 4)
    sz = jnp.asarray([4, 4, 4, 4], jnp.int32)
    with pytest.raises(ValueError, match="expects lhs"):
        grouped_matmul(lhs[0], rhs, sz)
    with pytest.raises(ValueError, match="mismatch"):
        grouped_matmul(lhs, rhs[:, :4], sz)
    with pytest.raises(ValueError, match="unknown grouped_matmul impl"):
        grouped_matmul(lhs, rhs, sz, impl="cuda")
