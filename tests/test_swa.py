"""Sliding-window attention: kernel numerics, gradients, and HF parity.

The reference inherits SWA from flash-attn's ``window_size``
(``05-training-llama-405b/train_llm.py:93``); here it is a banded extension
of the Pallas flash kernel (out-of-band kv tiles are skipped entirely —
O(S*window) cost) plus the matching mask on the XLA reference path. HF
semantics throughout: query i attends keys j with 0 <= i - j < window.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.ops.attention import multihead_attention
from distributed_training_guide_tpu.ops.flash_attention import flash_attention


def _dense_swa_reference(q, k, v, window):
    """O(S^2) numpy-ish reference with the explicit band mask."""
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    groups = hq // hkv
    qf = np.asarray(q, np.float32)
    kf = np.asarray(k, np.float32)
    vf = np.asarray(v, np.float32)
    out = np.zeros_like(qf)
    for h in range(hq):
        kh = kf[:, :, h // groups]
        vh = vf[:, :, h // groups]
        scores = np.einsum("bqd,bkd->bqk", qf[:, :, h], kh) / np.sqrt(d)
        i = np.arange(s)[:, None]
        j = np.arange(s)[None, :]
        mask = (i >= j) & ((i - j) < window)
        scores = np.where(mask, scores, -np.inf)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out[:, :, h] = np.einsum("bqk,bkd->bqd", p, vh)
    return out


@pytest.mark.parametrize("window", [1, 7, 16, 33, 64, 1000])
def test_flash_swa_matches_dense_reference(window):
    """Windows off, on, and straddling the 16-wide blocks the 64-seq case
    picks — including window=1 (self only) and window >= seq (== causal)."""
    rng = np.random.RandomState(0)
    b, s, hq, hkv, d = 2, 64, 4, 2, 32
    q = jnp.asarray(rng.randn(b, s, hq, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
    got = flash_attention(q, k, v, causal=True, window=window, interpret=True)
    want = _dense_swa_reference(q, k, v, window)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_flash_swa_grads_match_xla():
    """Full backward through the banded kernel vs the XLA banded mask."""
    rng = np.random.RandomState(1)
    b, s, hq, hkv, d, window = 1, 64, 4, 2, 32, 24

    q = jnp.asarray(rng.randn(b, s, hq, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, window=window, interpret=True)
        return jnp.sum(o * o)

    def loss_xla(q, k, v):
        o = multihead_attention(q, k, v, causal=True, window=window, impl="xla")
        return jnp.sum(o * o)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_window_requires_causal():
    q = jnp.zeros((1, 8, 2, 64))
    with pytest.raises(ValueError, match="requires causal"):
        flash_attention(q, q, q, causal=False, window=4, interpret=True)
    # the dispatcher must fail loudly on BOTH paths: the xla path used to
    # silently IGNORE the window when causal=False (advisor round 5)
    for impl in ("xla", "flash", "auto"):
        with pytest.raises(ValueError, match="requires causal"):
            multihead_attention(q, q, q, causal=False, window=4, impl=impl)


def test_window_below_one_raises_everywhere(eight_devices):
    """A static window < 1 masks every score — the kernel's safe_l path
    would return all-ZERO attention with no error (review finding: the
    per-call/dynamic paths skipped the static path's >= 1 guard). Every
    entry point must raise instead; negative layer_windows entries (whose
    traced column can't be checked at trace time) fail at the producer."""
    from distributed_training_guide_tpu.ops.flash_attention import (
        make_sharded_flash_attention)
    from distributed_training_guide_tpu.ops.ring_attention import (
        make_ring_attention)
    from distributed_training_guide_tpu.parallel import make_mesh

    q = jnp.zeros((2, 32, 4, 16))
    with pytest.raises(ValueError, match="window must be >= 1"):
        flash_attention(q, q, q, window=0, interpret=True)
    with pytest.raises(ValueError, match="window must be >= 1"):
        multihead_attention(q, q, q, causal=True, window=0, impl="xla")

    mesh = make_mesh(fsdp=2, devices=jax.devices()[:2])
    sharded = make_sharded_flash_attention(mesh, batch_axes=("fsdp",),
                                           head_axis=None)
    with pytest.raises(ValueError, match="window must be >= 1"):
        sharded(q, q, q, window=0)   # the per-call override path
    with pytest.raises(ValueError, match="window must be >= 1"):
        make_sharded_flash_attention(mesh, batch_axes=("fsdp",),
                                     head_axis=None, window=0)

    cp_mesh = make_mesh(cp=2, devices=jax.devices()[:2])
    ring = make_ring_attention(cp_mesh, data_axes=(), head_axis=None)
    with pytest.raises(ValueError, match="window must be >= 1"):
        ring(q, q, q, window=0)
    with pytest.raises(ValueError, match="window must be >= 1"):
        make_ring_attention(cp_mesh, data_axes=(), head_axis=None, window=0)

    from distributed_training_guide_tpu.models.llama import (
        LlamaConfig, _layer_window_column)

    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_layers=2, num_heads=2, num_kv_heads=2,
                      layer_windows=(8, -1))
    with pytest.raises(ValueError, match="layer_windows"):
        _layer_window_column(cfg)


def test_xla_swa_with_explicit_positions():
    """The decode path masks the KV cache through explicit kv_positions;
    the window must compose with them (cache rows beyond pos stay dead)."""
    rng = np.random.RandomState(2)
    b, s, h, d, window = 1, 16, 2, 8, 5
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    got = multihead_attention(q, k, v, causal=True, positions=pos,
                              kv_positions=pos, impl="xla", window=window)
    want = _dense_swa_reference(q, k, v, window)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_mistral_swa_parity(tmp_path):
    """End to end vs torch: a Mistral checkpoint whose sliding_window is
    NARROWER than the trained sequence — the exact case the round-4 warning
    refused. seq 48 > window 16 means over half of every late row's causal
    keys are out-of-band; full-causal attention would diverge wildly."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.models.hf_convert import (
        convert_hf_checkpoint, load_pretrained)
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    hf_cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        sliding_window=16, tie_word_embeddings=False,
        attn_implementation="eager")
    torch.manual_seed(0)
    model = transformers.MistralForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model(f"hf:{tmp_path / 'hf'}", dtype=jnp.float32)
    assert bundle.config.sliding_window == 16
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    shapes = jax.eval_shape(lambda: bundle.init(bundle.config, jax.random.key(0)))
    shardings = plan.param_shardings(bundle.param_logical_axes(bundle.config),
                                     shapes)
    params = load_pretrained(bundle, shardings, tmp_path / "conv")

    ids = np.random.RandomState(0).randint(0, 128, (2, 48))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def _cp_trajectory(bundle_kwargs, plan, steps=2, seq=64, **trainer_kwargs):
    """Short training trajectory (losses) for the CP parity goldens below."""
    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.train import Trainer, adamw_cosine

    bundle = get_model("llama-debug", dtype=jnp.float32, **bundle_kwargs)
    trainer = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), plan=plan,
                      donate=False, **trainer_kwargs)
    ids = np.random.RandomState(0).randint(0, 512, (4, seq))
    state = trainer.init_state(0)
    batch = {k: jax.device_put(jnp.asarray(ids), trainer.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    losses = []
    for _ in range(steps):
        state, m = trainer.step_fn(state, batch)
        losses.append(float(m["loss"]))
    return losses


def test_ring_cp_swa_matches_single_device():
    """sliding_window through the zigzag ring: every live chunk pair runs
    the kernel with its GLOBAL offsets on the dynamic band operand, so the
    band mask is exact across chunk boundaries — trajectory parity vs
    single device (this replaced the old loud rejection). window 16 < the
    32-token per-member slice, so the band crosses zigzag chunk boundaries
    and out-of-band chunk pairs genuinely skip."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    kwargs = dict(sliding_window=16)
    golden = _cp_trajectory(
        kwargs, make_plan("single", make_mesh(devices=jax.devices()[:1])))
    ring = _cp_trajectory(
        kwargs, make_plan("ddp", make_mesh(cp=2, devices=jax.devices()[:2])),
        context_impl="ring")
    np.testing.assert_allclose(ring, golden, rtol=2e-4)
    # a deeper ring: cp=4 exercises multi-hop band skipping
    ring4 = _cp_trajectory(
        kwargs, make_plan("ddp", make_mesh(cp=4, devices=jax.devices()[:4])),
        context_impl="ring")
    np.testing.assert_allclose(ring4, golden, rtol=2e-4)


def test_swa_remat_policy_keeps_banded_kernel_residuals():
    """The banded kernel under jax.checkpoint with the attn policy: the
    flash_out/flash_lse tags must still save (window is a nondiff static),
    so gradients match the un-remat'd ones AND backward avoids the full
    forward recompute (same pallas-call-count mechanism pin as the causal
    remat test — a tag drift would silently degrade to full recompute)."""
    from distributed_training_guide_tpu.train.step import REMAT_POLICIES

    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 64, 4, 32), jnp.float32)
    k = jnp.asarray(rng.randn(1, 64, 2, 32), jnp.float32)
    v = jnp.asarray(rng.randn(1, 64, 2, 32), jnp.float32)

    def f(q, k, v):
        o = flash_attention(q, k, v, causal=True, window=24,
                            block_q=32, block_k=32, interpret=True)
        return jnp.sum(o * o)

    ref = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(jax.checkpoint(f, policy=REMAT_POLICIES["attn"]),
                   argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(ref, got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)

    def n_pallas(policy):
        jaxpr = jax.make_jaxpr(
            jax.grad(jax.checkpoint(f, policy=REMAT_POLICIES[policy])))(q, k, v)
        return str(jaxpr).count("pallas_call")

    assert n_pallas("attn") < n_pallas("all"), \
        (n_pallas("attn"), n_pallas("all"))


def test_cp_gemma2_extras_match_single_device():
    """Gemma-2's attention extras — tanh softcap, query_pre_attn_scalar
    score scale, and the alternating per-layer window schedule — through
    BOTH CP schemes, trajectory parity vs single device (these combinations
    were loudly rejected before the kernels threaded the extras). The ring
    runs the banded per-pair kernels with softcap/scale baked in; ulysses
    passes them through its full-sequence layout. layer_windows alternates
    a 16-band with full attention at seq 64, so a uniform-window (or
    dropped-softcap) implementation cannot match."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    kwargs = dict(attn_logit_softcap=30.0, query_pre_attn_scalar=24.0,
                  layer_windows=(16, 0))
    golden = _cp_trajectory(
        kwargs, make_plan("single", make_mesh(devices=jax.devices()[:1])))
    ring = _cp_trajectory(
        kwargs, make_plan("ddp", make_mesh(cp=2, devices=jax.devices()[:2])),
        context_impl="ring")
    np.testing.assert_allclose(ring, golden, rtol=2e-4)
    ulysses = _cp_trajectory(
        kwargs, make_plan("ddp", make_mesh(cp=2, devices=jax.devices()[:2])),
        context_impl="ulysses")
    np.testing.assert_allclose(ulysses, golden, rtol=2e-4)


def test_callable_attn_impl_rejects_gemma2_attention_extras():
    """Mirror of the cp>1 check at cp=1: a user-supplied *callable*
    attn_impl carries no softcap/scale/layer_windows, so Gemma-2 extras
    would be silently dropped — the Trainer must reject the combination at
    build time (advisor round 5)."""
    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.train import Trainer, adamw_cosine

    def custom_attn(q, k, v, **kw):  # pragma: no cover — never reached
        return q

    bundle = get_model("llama-debug", attn_logit_softcap=50.0)
    with pytest.raises(ValueError, match="user-supplied attn_impl"):
        Trainer(bundle=bundle, optimizer=adamw_cosine(1e-4),
                attn_impl=custom_attn)
    # plain configs keep accepting callables (the supported extension point)
    Trainer(bundle=get_model("llama-debug"), optimizer=adamw_cosine(1e-4),
            attn_impl=custom_attn)
    # layer_windows ALONE composes with a callable that declares
    # accepts_window (the model passes window= per call, like the
    # Trainer-built wrappers); without the declaration it stays rejected
    lw_bundle = get_model("llama-debug", layer_windows=(16, 0))
    with pytest.raises(ValueError, match="user-supplied attn_impl"):
        Trainer(bundle=lw_bundle, optimizer=adamw_cosine(1e-4),
                attn_impl=custom_attn)

    def windowed_attn(q, k, v, **kw):  # pragma: no cover — never reached
        return q

    windowed_attn.accepts_window = True
    Trainer(bundle=lw_bundle, optimizer=adamw_cosine(1e-4),
            attn_impl=windowed_attn)
    # a UNIFORM sliding_window is gated the same way: silently training
    # full-causal against an SWA config is the failure mode being guarded
    sw_bundle = get_model("llama-debug", sliding_window=32)
    with pytest.raises(ValueError, match="user-supplied attn_impl"):
        Trainer(bundle=sw_bundle, optimizer=adamw_cosine(1e-4),
                attn_impl=custom_attn)
    Trainer(bundle=sw_bundle, optimizer=adamw_cosine(1e-4),
            attn_impl=windowed_attn)


def test_sharded_flash_per_call_static_window_override(eight_devices):
    """A per-call STATIC int window differing from the factory default must
    genuinely band (review finding: _resolve_band treats static ints as
    bake-in, and the override path once substituted the 2**30 no-band
    encoding — silently running full attention)."""
    from distributed_training_guide_tpu.ops.flash_attention import (
        make_sharded_flash_attention)
    from distributed_training_guide_tpu.parallel import make_mesh

    mesh = make_mesh(fsdp=2, devices=jax.devices()[:2])
    attn = make_sharded_flash_attention(mesh, batch_axes=("fsdp",),
                                        head_axis=None)
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(2, 32, 4, 16), jnp.float32)
    k = jnp.asarray(rng.randn(2, 32, 2, 16), jnp.float32)
    v = jnp.asarray(rng.randn(2, 32, 2, 16), jnp.float32)
    got = attn(q, k, v, window=8)
    want = _dense_swa_reference(q, k, v, 8)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)
    # and a per-call None against a WINDOWED factory lifts the band
    attn_w = make_sharded_flash_attention(mesh, batch_axes=("fsdp",),
                                          head_axis=None, window=8)
    full = multihead_attention(q, k, v, causal=True, impl="xla")
    got_full = attn_w(q, k, v, window=None)
    np.testing.assert_allclose(np.asarray(got_full), np.asarray(full),
                               rtol=2e-5, atol=2e-5)


def test_swa_train_step_and_ulysses_compose():
    """A real optimizer step with the window active (single device), and the
    Ulysses CP path accepting the window (full-seq layout during attention)."""
    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan
    from distributed_training_guide_tpu.train import Trainer, adamw_cosine

    ids = np.random.RandomState(0).randint(0, 512, (4, 64))
    losses = {}
    for name, window in (("full", None), ("swa", 16)):
        bundle = get_model("llama-debug", sliding_window=window,
                           dtype=jnp.float32)
        trainer = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-4),
                          plan=make_plan("single",
                                         make_mesh(devices=jax.devices()[:1])),
                          donate=False)
        state = trainer.init_state(0)
        batch = {k: jnp.asarray(ids) for k in ("input_ids", "labels")}
        _, m = trainer.step_fn(state, batch)
        losses[name] = float(m["loss"])
    assert np.isfinite(losses["swa"])
    # the band genuinely binds: different attention -> different loss
    assert abs(losses["swa"] - losses["full"]) > 1e-6

    bundle = get_model("llama-debug", sliding_window=16, dtype=jnp.float32)
    trainer = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-4),
                      plan=make_plan("ddp", make_mesh(cp=2,
                                     devices=jax.devices()[:2])),
                      context_impl="ulysses", donate=False)
    state = trainer.init_state(0)
    batch = {k: jax.device_put(jnp.asarray(ids),
                               trainer.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    _, m = trainer.step_fn(state, batch)
    assert np.isfinite(float(m["loss"]))
