"""Tiered KV (serve/tiering.py): host-RAM spill for prefix pages,
preempted sequences and idle adapters, plus the router's fleet-wide
prefix directory.

The contract under test is the pool discipline extended one tier down:
- preemption SPILLS the victim's live pages and resume is
  scatter-and-seat — token-bitwise vs the never-preempted batch-1
  reference (greedy AND temp>0, fp32 AND int8 pools: the int8 payload
  and its fp32 scale rows ride together), with NO re-prefill (pinned by
  prefill-call count);
- the extended capacity audit holds after EVERY iteration: the HBM
  identity (free + distinct held pages == capacity, refcount == holder
  count) is UNCHANGED by tiering — a spilled page freed its HBM slot at
  spill time — and the tier audits its own ledger (bytes_used ==
  sum(record bytes) <= budget, spilled_pages == sum(record pages));
- a fleet-directory hit on a cold replica seats the prefix with zero
  prefill forward passes over the pulled pages; any torn/stalled pull
  frame degrades to an ordinary cache miss (refuse-never-corrupt);
- adapter-namespaced prefix keys never cross tenants through the
  directory; adapter spill/restore round-trips bitwise;
- a generation swap carries the host tier when the payload-seat path is
  legal and drops it when replay is forced.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.serve import Request, ServeEngine
from distributed_training_guide_tpu.serve.api import generate_many
from distributed_training_guide_tpu.serve.kv_pages import pool_audit
from distributed_training_guide_tpu.serve.tiering import (HostTier,
                                                          prefix_digest,
                                                          pull_prefix)
from distributed_training_guide_tpu.utils import faults

pytestmark = pytest.mark.tiering


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


def _fresh(req):
    return dataclasses.replace(req, request_id=None)


def _ref_engine(bundle, params, **kw):
    return ServeEngine(bundle, params, n_slots=1, prefix_cache=False, **kw)


def _slot_holders(sched) -> dict:
    held: dict = {}
    for slot in sched.slots:
        if slot is None:
            continue
        assert 0 not in slot.pages, "trash page in a live table"
        for p in slot.pages:
            held[p] = held.get(p, 0) + 1
    return held


def _cache_refs(sched) -> dict:
    """page -> prefix-cache references, across EVERY adapter namespace."""
    refs: dict = {}
    if sched.cache is None:
        return refs
    stack = list(sched.cache._roots.values())
    while stack:
        node = stack.pop()
        for child in node.children.values():
            refs[child.page] = refs.get(child.page, 0) + 1
            stack.append(child)
    return refs


def _audit(eng) -> None:
    """The extended per-iteration audit: HBM identity + tier ledger."""
    sched = eng.scheduler
    pool_audit(sched.pool, [_slot_holders(sched), _cache_refs(sched)],
               tier=eng.host_tier)


# ---- preempt-spill-restore -------------------------------------------------

@pytest.mark.chaos
@pytest.mark.parametrize("kv_dtype", [
    None, pytest.param("int8", marks=pytest.mark.kvquant)])
def test_preempt_spill_restore_bitwise_identity(llama, kv_dtype):
    """The acceptance pin: a pool far below worst case forces real
    preemptions; with the host tier attached the victims' live pages
    spill and resume is scatter-and-seat — every request (greedy AND
    sampled) is token-bitwise vs batch-1, NO preempted sequence that
    restore-hits re-prefills (prefill calls == admissions + restore
    MISSES only), and the extended audit holds after every iteration."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=4, page_size=4, max_len=16,
                      n_pages=7, kv_dtype=kv_dtype,
                      host_tier_bytes=1 << 20)
    reqs = [Request(prompt_ids=[3 + i, 17, 42][:1 + i % 3],
                    max_new_tokens=6 + (i % 5),
                    temperature=0.8 if i % 2 else 0.0, seed=i)
            for i in range(8)]
    ids = [eng.submit(_fresh(r)) for r in reqs]
    done, it = {}, 0
    while eng.has_work:
        for res in eng.step():
            done[res.request_id] = res
        _audit(eng)
        it += 1
        assert it < 3000, "engine stalled"
    st = eng.stats()
    assert eng.scheduler.stats["preempted"] > 0   # real pressure
    assert st["restore_hits"] > 0                 # real spill-restores
    # resume is scatter-and-seat, not re-prefill: one bucket prefill per
    # ADMISSION, plus one only for each preempted entry whose restore
    # missed (which then re-admits through the recompute path)
    assert st["prefill_calls"] == len(reqs) + st["restore_misses"]
    ref_eng = _ref_engine(bundle, params, page_size=4, max_len=16,
                          kv_dtype=kv_dtype)
    for rid, req in zip(ids, reqs):
        ref = generate_many(ref_eng, [_fresh(req)])[0]
        assert done[rid].token_ids == ref.token_ids, \
            f"seed={req.seed} diverged across spill-restore"
    _audit(eng)                                   # drained and balanced


def test_stats_report_and_gauges_expose_tier(llama):
    """Observability satellite: the tier gauges ride stats() (the
    /healthz payload) and the kv_report grows host-tier rows."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                      host_tier_bytes=1 << 16)
    st = eng.stats()
    for key in ("host_tier_bytes", "host_tier_budget_bytes",
                "spilled_pages", "restore_hits", "restore_misses",
                "prefill_calls"):
        assert key in st, key
    assert st["host_tier_budget_bytes"] == 1 << 16
    rep = eng.kv_report()
    assert rep["host_tier_budget_bytes"] == 1 << 16
    assert "host_tier_page_capacity" in rep


# ---- HostTier ledger discipline --------------------------------------------

def test_host_tier_budget_lru_and_audit():
    """Unit discipline: byte budget is a hard ceiling (oversized put
    rejected, LRU evicted to fit), get touches recency, take consumes,
    and the ledger audits throughout."""
    rec = {"k": np.arange(10, dtype=np.float32)}      # 40 bytes
    tier = HostTier(budget_bytes=100)
    assert tier.put(("a",), rec, pages=1)
    assert tier.put(("b",), rec, pages=1)
    tier.audit()
    assert not tier.put(("big",), {"k": np.zeros(64, np.float32)})
    assert tier.counters["spill_rejects"] == 1
    tier.get(("a",))                                  # a is now MRU
    assert tier.put(("c",), rec, pages=1)             # evicts b (LRU)
    assert tier.get(("b",)) is None
    assert tier.counters["evictions"] == 1
    assert tier.spilled_pages == 2 and tier.bytes_used == 80
    taken = tier.take(("a",))
    assert np.array_equal(taken.payload["k"], rec["k"])
    assert tier.get(("a",)) is None and len(tier) == 1
    tier.audit()


# ---- fleet directory: zero-prefill pulls, torn frames, tenant isolation ----

def _warm_prefix():
    return [3 + (i % 60) for i in range(24)]          # 6 full pages


_FLEET_KW = dict(n_slots=2, page_size=4, max_len=64, prefill_chunk=4,
                 host_tier_bytes=1 << 20, share_programs=False)


def _warm_and_drain(bundle, params):
    """A 2-replica fleet with the shared prefix committed on one replica
    that then DRAINS — the next request for that prefix must land on the
    cold sibling (drained replicas stay live, so they remain legal pull
    SOURCES). Independent programs keep prefill counters per-replica."""
    from distributed_training_guide_tpu.serve.router import local_fleet

    fleet = local_fleet(bundle, params, 2, **_FLEET_KW)
    generate_many(fleet, [Request(prompt_ids=_warm_prefix() + [5],
                                  max_new_tokens=3)])
    fleet.step()                       # stats snapshot -> directory
    warm = [n for n, (_, keys) in fleet._directory.items() if keys][0]
    fleet.replicas[warm].drain()
    return fleet, warm


def _prefill_calls(fleet):
    return {n: r.engine.programs.prefill_calls
            for n, r in fleet.replicas.items()}


def test_directory_pull_seats_prefix_with_zero_prefill(llama):
    """The acceptance pin: a directory hit on a cold replica pulls the
    committed pages over the wire and seats them — the pulled replica
    runs exactly as many prefill forwards as a warm-LOCAL engine (the
    one residual chunk past the last full page; literally zero passes
    over the pulled pages), strictly fewer than the cold re-prefill."""
    bundle, params = llama
    probe = Request(prompt_ids=_warm_prefix() + [8], max_new_tokens=3)
    fleet, warm = _warm_and_drain(bundle, params)
    pc0 = _prefill_calls(fleet)
    res = generate_many(fleet, [_fresh(probe)])
    pc1 = _prefill_calls(fleet)
    dst = [n for n in fleet.replicas if n != warm][0]
    assert fleet.counters["directory_pulls"] == 1
    assert fleet.counters["directory_pull_hits"] == 1
    assert pc1[warm] == pc0[warm], "pull must only READ the source"
    pulled_calls = pc1[dst] - pc0[dst]

    warm_ctl = ServeEngine(bundle, params, n_slots=2, page_size=4,
                           max_len=64, prefill_chunk=4)
    generate_many(warm_ctl, [Request(prompt_ids=_warm_prefix() + [5],
                                     max_new_tokens=3)])
    c0 = warm_ctl.programs.prefill_calls
    warm_res = generate_many(warm_ctl, [_fresh(probe)])
    warm_calls = warm_ctl.programs.prefill_calls - c0

    cold_ctl = ServeEngine(bundle, params, n_slots=2, page_size=4,
                           max_len=64, prefill_chunk=4)
    cold_res = generate_many(cold_ctl, [_fresh(probe)])
    cold_calls = cold_ctl.programs.prefill_calls

    assert pulled_calls == warm_calls < cold_calls
    assert res[0].token_ids == warm_res[0].token_ids \
        == cold_res[0].token_ids
    for r in fleet.replicas.values():
        _audit(r.engine)


@pytest.mark.chaos
def test_torn_directory_pull_degrades_to_clean_reprefill(llama,
                                                         monkeypatch):
    """A pull frame torn on the wire (sender crash -> CRC NAK) is an
    ordinary cache miss, never corruption: the routed replica re-prefills
    the full prompt, tokens stay identical to the cold reference, and
    both replicas audit clean after every iteration."""
    bundle, params = llama
    # router xfer ids count from 1 -> the FIRST pull is the torn one
    monkeypatch.setenv(faults.ENV_HANDOFF_CRASH_XFER, "1")
    probe = Request(prompt_ids=_warm_prefix() + [8], max_new_tokens=3)
    fleet, warm = _warm_and_drain(bundle, params)
    pc0 = _prefill_calls(fleet)
    fleet.submit(_fresh(probe))
    done, it = [], 0
    while fleet.has_work:
        done.extend(fleet.step())
        for r in fleet.replicas.values():
            _audit(r.engine)
        it += 1
        assert it < 2000
    assert fleet.counters["directory_pulls"] == 1
    assert fleet.counters["directory_pull_hits"] == 0
    assert fleet.counters["directory_pull_failures"] == 1
    dst = [n for n in fleet.replicas if n != warm][0]
    cold_ctl = ServeEngine(bundle, params, n_slots=2, page_size=4,
                           max_len=64, prefill_chunk=4)
    cold_res = generate_many(cold_ctl, [_fresh(probe)])
    # the plain miss: full cold re-prefill, identical tokens
    assert (_prefill_calls(fleet)[dst] - pc0[dst]
            == cold_ctl.programs.prefill_calls)
    assert done[0].token_ids == cold_res[0].token_ids


@pytest.mark.chaos
@pytest.mark.parametrize("knob,xfer,reason", [
    (faults.ENV_HANDOFF_CRASH_XFER, 5, "dropped_nak"),
    (faults.ENV_HANDOFF_TIMEOUT_XFER, 6, "dropped_timeout"),
])
def test_pull_prefix_wire_faults_leave_dst_cold(llama, monkeypatch,
                                                knob, xfer, reason):
    """Both wire failure modes at the pull primitive: torn bytes and a
    stalled receiver end with ok=False, NOTHING half-seated on the
    destination, and the destination still serves the request identical
    to its own cold reference."""
    bundle, params = llama
    tokens = _warm_prefix() + [8]
    kw = dict(n_slots=2, page_size=4, max_len=64, prefill_chunk=4)
    src = ServeEngine(bundle, params, host_tier_bytes=1 << 20, **kw)
    generate_many(src, [Request(prompt_ids=_warm_prefix() + [5],
                                max_new_tokens=3)])
    dst = ServeEngine(bundle, params, host_tier_bytes=1 << 20, **kw)
    monkeypatch.setenv(knob, str(xfer))
    out = pull_prefix(src, dst, tokens, xfer_id=xfer, ack_timeout_s=0.2)
    assert out["ok"] is False and out["reason"] == reason
    assert dst.scheduler.cache.chain_depth(tokens) == 0
    _audit(dst)
    monkeypatch.delenv(knob)
    got = generate_many(dst, [Request(prompt_ids=tokens,
                                      max_new_tokens=3)])[0]
    ref = generate_many(
        ServeEngine(bundle, params, **kw),
        [Request(prompt_ids=tokens, max_new_tokens=3)])[0]
    assert got.token_ids == ref.token_ids


def test_adapter_namespaced_prefix_keys_never_cross_tenants(llama):
    """Tenant isolation through the directory: the prefix key is salted
    by adapter id, so tenant A's committed chain is invisible to a base
    (or other-tenant) request — a cross-tenant pull finds the source
    COLD, and a matching-tenant pull seats only under that namespace."""
    from distributed_training_guide_tpu.models.lora import lora_bundle
    from distributed_training_guide_tpu.serve.tiering import \
        cache_prefix_keys

    bundle, params = llama
    tokens = _warm_prefix() + [8]
    assert prefix_digest(tokens, 0) != prefix_digest(tokens, 1)

    wrapped = lora_bundle(bundle, rank=4)
    shapes = jax.eval_shape(
        lambda: wrapped.init(wrapped.config, jax.random.key(0)))["lora"]
    leaves, treedef = jax.tree.flatten(shapes)
    adapter = jax.tree.unflatten(treedef, [
        0.2 * jax.random.normal(k, leaf.shape, jnp.float32)
        for k, leaf in zip(jax.random.split(jax.random.key(1),
                                            len(leaves)), leaves)])
    kw = dict(n_slots=2, page_size=4, max_len=64, prefill_chunk=4,
              max_adapters=2, adapter_rank=4, host_tier_bytes=1 << 20)
    src = ServeEngine(bundle, params, **kw)
    slot = src.publish_adapter(adapter, name="tenant")
    generate_many(src, [Request(prompt_ids=_warm_prefix() + [5],
                                max_new_tokens=3, adapter_id=slot)])
    keys = cache_prefix_keys(src.scheduler.cache)
    assert prefix_digest(_warm_prefix(), slot).hex() in keys
    assert prefix_digest(_warm_prefix(), 0).hex() not in keys

    dst = ServeEngine(bundle, params, **kw)
    # cross-tenant: the base namespace must NOT see tenant pages
    out = pull_prefix(src, dst, tokens, adapter_id=0)
    assert out["ok"] is False and out["reason"] == "src_cold"
    assert dst.scheduler.cache.chain_depth(tokens, ns=0) == 0
    # matching tenant: seats, and ONLY under the tenant namespace
    out = pull_prefix(src, dst, tokens, adapter_id=slot)
    assert out["ok"] and out["pages"] == 6
    assert dst.scheduler.cache.chain_depth(tokens, ns=slot) == 6
    assert dst.scheduler.cache.chain_depth(tokens, ns=0) == 0
    _audit(dst)


# ---- adapter spill past max_adapters ---------------------------------------

def test_adapter_spill_restore_roundtrip_bitwise(llama):
    """AdapterPool eviction under pressure spills the idle tenant's A/B
    rows to the host tier; restore_adapter re-seats them through the
    compiled insert — the stacks rows land bitwise what the spill
    gathered, with no fleet republish."""
    from distributed_training_guide_tpu.models.lora import lora_bundle

    bundle, params = llama
    wrapped = lora_bundle(bundle, rank=4)
    shapes = jax.eval_shape(
        lambda: wrapped.init(wrapped.config, jax.random.key(0)))["lora"]
    leaves, treedef = jax.tree.flatten(shapes)

    def adapter(seed):
        keys = jax.random.split(jax.random.key(seed), len(leaves))
        return jax.tree.unflatten(treedef, [
            0.2 * jax.random.normal(k, leaf.shape, jnp.float32)
            for k, leaf in zip(keys, leaves)])

    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                      max_adapters=3, adapter_rank=4,
                      host_tier_bytes=1 << 24)
    s1 = eng.publish_adapter(adapter(1), name="t1")
    rows1 = {t: {leaf: np.asarray(pair[leaf][:, s1])
                 for leaf in ("a", "b")}
             for t, pair in eng.programs.adapter_stacks.items()}
    eng.publish_adapter(adapter(2), name="t2")
    eng.publish_adapter(adapter(3), name="t3")  # pool full -> evicts t1 (LRU)
    assert eng.programs.adapter_pool.stats["spill_evictions"] == 1
    assert eng.host_tier.get(("adapter", "t1")) is not None

    back = eng.restore_adapter("t1")
    assert back is not None
    assert eng.host_tier.get(("adapter", "t1")) is None  # consumed
    for t, pair in eng.programs.adapter_stacks.items():
        for leaf in ("a", "b"):
            assert np.array_equal(np.asarray(pair[leaf][:, back]),
                                  rows1[t][leaf]), (t, leaf)
    # unknown tenants restore to None, not garbage
    assert eng.restore_adapter("never-spilled") is None


# ---- generation swaps -------------------------------------------------------

def test_generation_swap_carries_and_drops_tier(llama):
    """Elastic seam: a payload-compatible swap CARRIES the host tier's
    records into the new generation (budget threaded through
    new_generation); a forced-replay swap DROPS them — old-policy k/v
    must not survive a seat path that recomputes."""
    from distributed_training_guide_tpu.serve.elastic import (
        new_generation, swap_generation)

    bundle, params = llama

    def seeded_engine():
        eng = ServeEngine(bundle, params, n_slots=2, page_size=4,
                          max_len=16, host_tier_bytes=1 << 20)
        payload = eng.gather_pages([1])
        assert eng.host_tier.put(("prefix", 0, (3, 17, 42, 7)), payload,
                                 pages=1)
        return eng

    old = seeded_engine()
    new = new_generation(old, n_slots=4)
    assert new.host_tier.budget_bytes == old.host_tier.budget_bytes
    _, stats = swap_generation(old, new)
    assert stats["tier_records_carried"] == 1
    assert stats["tier_records_dropped"] == 0
    assert new.host_tier.get(("prefix", 0, (3, 17, 42, 7))) is not None
    assert len(old.host_tier) == 0
    new.host_tier.audit()

    old2 = seeded_engine()
    new2 = new_generation(old2)
    _, stats2 = swap_generation(old2, new2, force_replay=True)
    assert stats2["tier_records_carried"] == 0
    assert stats2["tier_records_dropped"] == 1
    assert len(new2.host_tier) == 0 and len(old2.host_tier) == 0


# ---- disaggregated pair -----------------------------------------------------

@pytest.mark.disagg
def test_disagg_preempt_spill_restore_identity(llama):
    """The same preempt-spill-restore contract through the
    prefill/decode split: decode-side preemptions spill from the decode
    pool, the facade restores ahead of re-admission, and every request
    is token-identical whether its restore HIT (scatter-and-seat) or
    MISSED (the refuse-don't-corrupt fallback re-prefills)."""
    from distributed_training_guide_tpu.serve.disagg import DisaggEngine

    bundle, params = llama
    reqs = [Request(prompt_ids=[3 + i, 17, 42][:1 + i % 3],
                    max_new_tokens=6 + (i % 5),
                    temperature=0.8 if i % 2 else 0.0, seed=i)
            for i in range(8)]
    eng = DisaggEngine(bundle, params, n_slots=4, page_size=4, max_len=16,
                       n_pages=7, n_prefill_pages=9,
                       transport="cross_host", host_tier_bytes=1 << 20)
    res = generate_many(eng, reqs, max_iterations=3000)
    s = eng.stats()
    assert s["preempted"] > 0
    assert s["restore_hits"] + s["restore_misses"] > 0
    eng.host_tier.audit()
    assert eng.decode_pool.n_free == eng.decode_pool.capacity
    ref_eng = _ref_engine(bundle, params, page_size=4, max_len=16)
    for got, req in zip(res, reqs):
        ref = generate_many(ref_eng, [_fresh(req)])[0]
        assert got.token_ids == ref.token_ids, \
            f"seed={req.seed} diverged through the disagg spill path"
