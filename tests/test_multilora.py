"""Batched multi-LoRA serving (serve/adapters.py + the grouped-GEMM lora
decode path in models/llama.py).

The contract under test:

- **identity**: an adapter decoded solo equals the same request decoded
  co-resident with other tenants; adapter 0 equals today's engine
  BITWISE (greedy and temp>0, spec-on and spec-off); a tenant's pooled
  decode matches a dedicated engine built from the merged weights.
- **retrace-free tenancy**: insert / republish / evict never retrace —
  the adapter stacks and per-slot ids are program ARGUMENTS, and the
  insert is one cached jit with a traced slot index. Pinned by
  ``jit_cache_sizes`` staying flat across churn, and by the lowered
  decode containing no dense per-adapter ``W + scale*A@B`` merge.
- **pool discipline**: the kv_pages lifecycle on adapter slots —
  refcounted by in-flight requests, LRU eviction only among idle
  tenants, slot 0 reserved as the zero adapter, loud refusals.
- **isolation**: prefix-cache pages are namespaced per adapter slot; a
  recycled slot id never serves the old tenant's cached prefixes.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.models.lora import (lora_bundle,
                                                        mask_optimizer,
                                                        merge_lora)
from distributed_training_guide_tpu.serve.adapters import (
    AdapterPool, adapter_nbytes, adapter_pool_bytes, adapter_shapes,
    validate_adapter_params)
from distributed_training_guide_tpu.serve.api import generate_many
from distributed_training_guide_tpu.serve.engine import ServeEngine
from distributed_training_guide_tpu.serve.scheduler import (RefusalError,
                                                            Request)
from distributed_training_guide_tpu.utils import hlo as hlo_util

pytestmark = [pytest.mark.serve, pytest.mark.multilora]

RANK = 4


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


@pytest.fixture(scope="module")
def wrapped(llama):
    return lora_bundle(llama[0], rank=RANK)


def _adapter(wrapped_bundle, seed: int, scale: float = 0.2) -> dict:
    """A NONTRIVIAL adapter payload: both factors random (the training
    init zeroes B, which would make every identity test vacuous)."""
    shapes = jax.eval_shape(
        lambda: wrapped_bundle.init(wrapped_bundle.config,
                                    jax.random.key(0)))["lora"]
    leaves, treedef = jax.tree.flatten(shapes)
    keys = jax.random.split(jax.random.key(seed), len(leaves))
    return jax.tree.unflatten(treedef, [
        scale * jax.random.normal(k, leaf.shape, jnp.float32)
        for k, leaf in zip(keys, leaves)])


def _reqs(specs):
    """Fresh Request objects per engine (results carry identity)."""
    return [Request(**spec) for spec in [dict(s) for s in specs]]


MIXED_SPECS = (
    # greedy and stochastic lanes for base and tenant traffic in ONE
    # batch — the bitwise pins below always cover both sampling paths
    dict(prompt_ids=[3, 5, 7, 11], max_new_tokens=8, seed=0),
    dict(prompt_ids=[4, 6, 8, 12], max_new_tokens=8, seed=1,
         temperature=0.8, top_k=5),
)


def _tokens(engine, specs):
    return [r.token_ids for r in generate_many(engine, _reqs(specs))]


# ---------------------------------------------------------------------------
# pool discipline
# ---------------------------------------------------------------------------

def test_adapter_pool_discipline():
    pool = AdapterPool(4, rank=8)
    assert pool.capacity == 3 and pool.n_free == 3 and pool.n_live == 0
    assert pool.scale == 2.0                      # alpha 16 / rank 8
    assert pool.is_live(0)                        # the zero adapter
    assert not pool.is_live(True)                 # bools are not slots
    assert not pool.is_live(1)

    a = pool.alloc("a")
    b = pool.alloc("b")
    c = pool.alloc("c")
    assert sorted([a, b, c]) == [1, 2, 3]
    assert pool.live_slots() == [1, 2, 3] and pool.n_free == 0
    assert pool.name_of(a) == "a"

    # refcounts: retain/release symmetric, loud on misuse
    pool.retain(a)
    assert pool.refcount(a) == 1
    pool.release(a)
    with pytest.raises(ValueError, match="double release"):
        pool.release(a)
    pool.retain(0)                                # no-op, never raises
    pool.release(0)
    with pytest.raises(ValueError):
        pool.retain(4)                            # out of range
    pool.evict(b)
    with pytest.raises(ValueError, match="not live"):
        pool.retain(b)

    # evict refuses while referenced; slot 0 never evictable
    pool.retain(a)
    with pytest.raises(ValueError, match="in-flight"):
        pool.evict(a)
    with pytest.raises(ValueError, match="never evictable"):
        pool.evict(0)

    # pressure: a is referenced, c idle -> LRU evicts c, not a
    d = pool.alloc("d")
    assert d == b                                 # the freed slot first
    pool.mark_update(d)                           # d most recently used
    e = pool.alloc("e")                           # pressure: no free slot
    assert e == c                                 # LRU idle tenant
    assert pool.stats["lru_evictions"] == 1
    pool.retain(d)
    f = pool.alloc("f")                           # only e is idle now
    assert f == e
    pool.release(a)
    pool.release(d)
    assert pool.alloc("g") in (a, d)              # idle again
    assert pool.stats["inserts"] == 7


def test_adapter_pool_alloc_none_when_all_referenced():
    pool = AdapterPool(3, rank=4)
    a, b = pool.alloc("a"), pool.alloc("b")
    pool.retain(a)
    pool.retain(b)
    before = dict(pool.stats)
    assert pool.alloc("c") is None                # nothing mutated
    assert dict(pool.stats) == before
    assert pool.live_slots() == sorted([a, b])


def test_adapter_pool_validation():
    with pytest.raises(ValueError, match="max_adapters"):
        AdapterPool(1, rank=4)
    with pytest.raises(ValueError, match="unknown adapter targets"):
        AdapterPool(4, rank=4, targets=("wq", "nope"))


def test_validate_adapter_params_loud(llama, wrapped):
    bundle, _ = llama
    shapes = adapter_shapes(bundle.config, rank=RANK, bundle=bundle)
    good = _adapter(wrapped, 1)
    validate_adapter_params(shapes, good)
    with pytest.raises(ValueError, match="target"):
        validate_adapter_params(shapes, {"wq": good["wq"]})
    bad_leaf = {t: dict(v) for t, v in good.items()}
    bad_leaf["wq"] = {"a": good["wq"]["a"]}
    with pytest.raises(ValueError):
        validate_adapter_params(shapes, bad_leaf)
    bad_shape = {t: dict(v) for t, v in good.items()}
    bad_shape["wq"]["a"] = good["wq"]["a"][:, :, :-1]
    with pytest.raises(ValueError, match="shape"):
        validate_adapter_params(shapes, bad_shape)
    bad_dtype = {t: dict(v) for t, v in good.items()}
    bad_dtype["wq"]["a"] = good["wq"]["a"].astype(jnp.int32)
    with pytest.raises(ValueError):
        validate_adapter_params(shapes, bad_dtype)


def test_adapter_bytes_arithmetic(llama):
    bundle, _ = llama
    cfg = bundle.config
    shapes = adapter_shapes(cfg, rank=RANK, bundle=bundle)
    manual = sum(
        int(np.prod(shapes[t]["a"])) + int(np.prod(shapes[t]["b"]))
        for t in shapes) * 4
    assert adapter_nbytes(cfg, rank=RANK, bundle=bundle) == manual
    assert adapter_pool_bytes(cfg, max_adapters=8, rank=RANK,
                              bundle=bundle) == 8 * manual


# ---------------------------------------------------------------------------
# identity pins
# ---------------------------------------------------------------------------

def test_zero_adapter_is_base_engine_bitwise(llama):
    """A pooled engine serving only adapter-0 traffic is bitwise
    today's engine — greedy AND temp>0, spec-off and spec-on."""
    bundle, params = llama
    kw = dict(n_slots=2, page_size=8, max_len=48)
    plain = _tokens(ServeEngine(bundle, params, **kw), MIXED_SPECS)
    pooled = _tokens(ServeEngine(bundle, params, max_adapters=4,
                                 adapter_rank=RANK, **kw), MIXED_SPECS)
    assert pooled == plain
    spec_kw = dict(kw, speculate="ngram", spec_k=4)
    plain_spec = _tokens(ServeEngine(bundle, params, **spec_kw),
                         MIXED_SPECS)
    pooled_spec = _tokens(ServeEngine(bundle, params, max_adapters=4,
                                      adapter_rank=RANK, **spec_kw),
                          MIXED_SPECS)
    assert plain_spec == plain                    # spec identity, base
    assert pooled_spec == plain                   # ...and pooled


def test_adapter_matches_merged_engine(llama, wrapped):
    """A pooled tenant decode equals a dedicated engine built from the
    merged weights (greedy and temp>0) — the pooled grouped-GEMM delta
    IS ``W + scale*A@B``, just never materialized."""
    bundle, params = llama
    payload = _adapter(wrapped, 7)
    kw = dict(n_slots=2, page_size=8, max_len=48)
    eng = ServeEngine(bundle, params, max_adapters=4, adapter_rank=RANK,
                      **kw)
    slot = eng.publish_adapter(payload, name="tenant")
    specs = [dict(s, adapter_id=slot) for s in MIXED_SPECS]
    pooled = _tokens(eng, specs)
    merged = merge_lora(wrapped, {"base": params, "lora": payload})
    ref = _tokens(ServeEngine(bundle, merged, **kw), MIXED_SPECS)
    assert pooled == ref


def test_solo_equals_coresident(llama, wrapped):
    """Adapter-batch-of-1 == the same request co-resident with another
    tenant and base traffic: no cross-slot leakage, no batch-shape
    dependence in the delta."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=4, page_size=8, max_len=48,
                      max_adapters=4, adapter_rank=RANK)
    s1 = eng.publish_adapter(_adapter(wrapped, 1), name="a")
    s2 = eng.publish_adapter(_adapter(wrapped, 2), name="b")
    probe = dict(prompt_ids=[9, 13, 17], max_new_tokens=8, seed=3,
                 temperature=0.7, top_k=8, adapter_id=s1)
    solo = _tokens(eng, [probe])
    mixed_specs = [
        probe,
        dict(prompt_ids=[2, 4, 6], max_new_tokens=8, seed=4,
             adapter_id=s2),
        dict(prompt_ids=[5, 10, 15], max_new_tokens=8, seed=5),
    ]
    mixed = _tokens(eng, mixed_specs)
    assert mixed[0] == solo[0]
    # and the base request in the mixed batch matches a plain engine
    base_ref = _tokens(
        ServeEngine(bundle, params, n_slots=4, page_size=8, max_len=48),
        [mixed_specs[2]])
    assert mixed[2] == base_ref[0]


def test_spec_identity_with_adapters(llama, wrapped):
    """spec-on == spec-off for tenant traffic: the verify program
    applies the same grouped deltas as decode, so exact acceptance
    keeps multi-LoRA streams bitwise."""
    bundle, params = llama
    payload = _adapter(wrapped, 11)
    kw = dict(n_slots=2, page_size=8, max_len=64,
              max_adapters=4, adapter_rank=RANK)
    prompt = [7, 11, 13, 7, 11, 13, 7, 11, 13]
    specs = [dict(prompt_ids=prompt, max_new_tokens=16, seed=0),
             dict(prompt_ids=prompt, max_new_tokens=16, seed=1,
                  temperature=0.8, top_k=5)]

    eng_off = ServeEngine(bundle, params, **kw)
    slot = eng_off.publish_adapter(payload, name="t")
    tenant_specs = [dict(s, adapter_id=slot) for s in specs]
    off = _tokens(eng_off, tenant_specs)

    eng_on = ServeEngine(bundle, params, speculate="ngram", spec_k=4,
                         **kw)
    assert eng_on.publish_adapter(payload, name="t") == slot
    on = _tokens(eng_on, tenant_specs)
    assert on == off
    assert eng_on.spec["spec_steps"] > 0          # speculation actually ran


def test_multilora_under_int8_weights(llama, wrapped):
    """The pool composes with block-quantized base weights: adapter-0
    stays bitwise the plain int8 engine, and a tenant's fp32 delta
    rides the int8 base (solo == co-resident there too)."""
    bundle, params = llama
    kw = dict(n_slots=2, page_size=8, max_len=48, weight_dtype="int8")
    plain = _tokens(ServeEngine(bundle, params, **kw), MIXED_SPECS)
    eng = ServeEngine(bundle, params, max_adapters=4, adapter_rank=RANK,
                      **kw)
    assert _tokens(eng, MIXED_SPECS) == plain     # adapter 0 == base
    slot = eng.publish_adapter(_adapter(wrapped, 5), name="t")
    probe = dict(prompt_ids=[9, 13, 17], max_new_tokens=8, seed=2,
                 adapter_id=slot)
    solo = _tokens(eng, [probe])
    assert solo[0] != plain[0][:len(solo[0])]     # the delta is live
    mixed = _tokens(eng, [probe, MIXED_SPECS[0]])
    assert mixed[0] == solo[0]


# ---------------------------------------------------------------------------
# admission + refusals
# ---------------------------------------------------------------------------

def test_unknown_adapter_refused(llama):
    bundle, params = llama
    plain = ServeEngine(bundle, params, n_slots=2, page_size=8,
                        max_len=32)
    with pytest.raises(RefusalError) as exc:
        plain.submit(Request(prompt_ids=[3], max_new_tokens=2,
                             adapter_id=1))
    assert exc.value.reason == "unknown_adapter"

    pooled = ServeEngine(bundle, params, n_slots=2, page_size=8,
                         max_len=32, max_adapters=4, adapter_rank=RANK)
    with pytest.raises(RefusalError) as exc:
        pooled.submit(Request(prompt_ids=[3], max_new_tokens=2,
                              adapter_id=3))
    assert exc.value.reason == "unknown_adapter"
    assert exc.value.http_status == 404
    with pytest.raises(RefusalError) as exc:
        pooled.submit(Request(prompt_ids=[3], max_new_tokens=2,
                              adapter_id="fast"))
    assert exc.value.reason == "bad_params"
    with pytest.raises(RefusalError) as exc:
        pooled.submit(Request(prompt_ids=[3], max_new_tokens=2,
                              adapter_id=True))
    assert exc.value.reason == "bad_params"
    assert pooled.stats()["refused"]["unknown_adapter"] == 1


def test_scheduler_refcount_lifecycle(llama, wrapped):
    """In-flight requests hold their tenant's slot: evict refuses
    mid-stream and succeeds after drain; drain_queue releases queued
    holders too."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=8, max_len=48,
                      max_adapters=4, adapter_rank=RANK)
    slot = eng.publish_adapter(_adapter(wrapped, 3), name="t")
    pool = eng.adapter_pool
    eng.submit(Request(prompt_ids=[3, 5], max_new_tokens=12,
                       adapter_id=slot))
    eng.submit(Request(prompt_ids=[4, 6], max_new_tokens=12,
                       adapter_id=slot))
    assert pool.refcount(slot) == 2
    eng.step()
    with pytest.raises(ValueError, match="in-flight"):
        eng.evict_adapter(slot)
    while eng.has_work:
        eng.step()
    assert pool.refcount(slot) == 0
    assert eng.stats()["adapter_requests"] == {slot: 2}
    eng.evict_adapter(slot)
    assert not pool.is_live(slot)


# ---------------------------------------------------------------------------
# retrace-free tenancy
# ---------------------------------------------------------------------------

def test_jit_caches_flat_across_adapter_churn(llama, wrapped):
    """Insert / republish / evict with a CONSTANT workload: every jit
    cache size stays exactly flat — tenancy is data, not programs.
    (prefix_cache off: the cache's own hit-path commit entry is a
    pre-existing, adapter-independent retrace.)"""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=8, max_len=48,
                      prefix_cache=False, max_adapters=4,
                      adapter_rank=RANK)
    payloads = [_adapter(wrapped, s) for s in (1, 2, 3)]
    s1 = eng.publish_adapter(payloads[0], name="t0")

    def run():
        specs = [dict(prompt_ids=[3, 5, 7], max_new_tokens=6, seed=0),
                 dict(prompt_ids=[3, 5, 8], max_new_tokens=6, seed=1,
                      temperature=0.8, top_k=5, adapter_id=s1)]
        return _tokens(eng, specs)

    run()
    run()                                         # both admission paths warm
    sizes0 = dict(eng.programs.jit_cache_sizes())
    assert sizes0.get("adapter_insert") == 1
    for i, payload in enumerate(payloads):
        fresh = eng.publish_adapter(payload, name=f"t{i + 1}")
        eng.publish_adapter(payloads[0], slot=s1)  # republish in place
        eng.evict_adapter(fresh)
        run()
        assert dict(eng.programs.jit_cache_sizes()) == sizes0, \
            f"adapter churn round {i} retraced"


def test_decode_hlo_no_merged_weight_materialization(llama, wrapped):
    """The lowered pooled decode contains the stacked factors and NO
    dense per-adapter merged projection: the delta flows through the
    ragged grouped GEMM at rank width, never through a ``[G, in, out]``
    (or per-slot ``[S, in, out]``) weight tensor."""
    bundle, params = llama
    cfg = bundle.config
    # n_slots chosen to collide with NO model dim (llama-debug has 2
    # layers, so n_slots=2 would alias the stacked base weight [L, e, h])
    n_slots, max_adapters = 3, 4
    eng = ServeEngine(bundle, params, n_slots=n_slots, page_size=8,
                      max_len=32, max_adapters=max_adapters,
                      adapter_rank=RANK)
    eng.publish_adapter(_adapter(wrapped, 1), name="t")
    arr = eng.scheduler.decode_arrays()
    lora_args = eng.programs.lora_call_args(arr["adapters"])
    text = eng._decode_fn.lower(
        eng.params, eng.pages["k"], eng.pages["v"],
        jnp.asarray(arr["tokens"]), jnp.asarray(arr["lengths"]),
        jnp.asarray(arr["tables"]), jnp.asarray(arr["seeds"]),
        jnp.asarray(arr["temps"]), jnp.asarray(arr["top_ks"]),
        jnp.asarray(arr["top_ps"]), jnp.asarray(arr["actives"]),
        *lora_args).as_text()
    e = cfg.hidden_size
    hq = cfg.num_heads * cfg.head_size
    hkv = cfg.num_kv_heads * cfg.head_size
    l = cfg.num_layers
    # the stacked factors ARE in the program (the lora path is live)...
    assert hlo_util.has_aval(text, "f32", (l, max_adapters, e, RANK))
    assert hlo_util.has_aval(text, "f32", (l, max_adapters, RANK, hq))
    # ...but no merged per-adapter (or per-slot) projection ever exists
    for fan_out in (hq, hkv):
        assert not hlo_util.has_shape_run(text, (max_adapters, e, fan_out))
        assert not hlo_util.has_shape_run(text, (n_slots, e, fan_out))


# ---------------------------------------------------------------------------
# prefix-cache namespacing
# ---------------------------------------------------------------------------

def test_prefix_cache_namespaced_per_adapter(llama, wrapped):
    """The same prompt under two tenants shares NOTHING: cached pages
    hold k/v computed under one adapter's deltas. Same-tenant reuse
    still hits; a recycled slot id starts from an empty namespace."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=48,
                      max_adapters=4, adapter_rank=RANK)
    slot = eng.publish_adapter(_adapter(wrapped, 1), name="a")
    prompt = list(range(3, 3 + 12))               # 3 full pages cacheable

    def one(adapter_id):
        return generate_many(eng, [Request(
            prompt_ids=prompt, max_new_tokens=2, adapter_id=adapter_id)])

    one(0)
    assert eng.stats()["prefix_hits"] == 0
    one(0)                                        # same tenant: hit
    assert eng.stats()["prefix_hits"] == 1
    one(slot)                                     # other tenant: MISS
    assert eng.stats()["prefix_hits"] == 1
    one(slot)                                     # its own namespace: hit
    assert eng.stats()["prefix_hits"] == 2
    # recycling the slot id drops the namespace with its pages
    held = eng.scheduler.cache_pages_held()
    assert held > 0
    eng.evict_adapter(slot)
    assert eng.scheduler.cache_pages_held() < held
    new_slot = eng.publish_adapter(_adapter(wrapped, 2), name="b")
    assert new_slot == slot                       # the recycled id
    one(new_slot)                                 # must NOT hit a's pages
    assert eng.stats()["prefix_hits"] == 2


# ---------------------------------------------------------------------------
# stats + reports
# ---------------------------------------------------------------------------

def test_engine_stats_and_adapter_report(llama, wrapped):
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=8, max_len=32,
                      max_adapters=4, adapter_rank=RANK)
    s0 = eng.stats()
    seq0 = s0["stats_seq"]
    assert s0["adapter_slots"] == 4 and s0["adapter_capacity"] == 3
    assert s0["adapters_live"] == 0 and s0["adapter_occupancy"] == 0.0
    slot = eng.publish_adapter(_adapter(wrapped, 1), name="t")
    generate_many(eng, [Request(prompt_ids=[3], max_new_tokens=2,
                                adapter_id=slot),
                        Request(prompt_ids=[4], max_new_tokens=2)])
    s1 = eng.stats()
    assert s1["adapters_live"] == 1
    assert s1["adapter_occupancy"] == round(1 / 3, 3)
    assert s1["adapter_inserts"] == 1 and s1["adapter_publishes"] == 1
    assert s1["adapter_requests"] == {slot: 1, 0: 1}
    assert s1["stats_seq"] > seq0                 # the seq is unchanged

    rep = eng.adapter_report()
    per = adapter_nbytes(bundle.config, rank=RANK, bundle=bundle)
    assert rep["bytes_per_adapter"] == per
    assert rep["pool_bytes"] == 4 * per
    assert rep["publish_payload_bytes"] == per
    assert rep["max_adapters"] == 4 and rep["rank"] == RANK

    # a pool-less engine publishes NO adapter keys (stats shape is
    # backward compatible)
    plain = ServeEngine(bundle, params, n_slots=2, page_size=8,
                        max_len=32)
    assert "adapter_slots" not in plain.stats()
    assert plain.adapter_report() == {}


def test_publish_adapter_busy_refusal_and_force(llama, wrapped):
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=8, max_len=48,
                      max_adapters=4, adapter_rank=RANK)
    payload = _adapter(wrapped, 1)
    eng.submit(Request(prompt_ids=[3, 5], max_new_tokens=8))
    eng.step()
    with pytest.raises(RuntimeError, match="in flight"):
        eng.publish_adapter(payload, name="t")
    assert eng.adapter_pool.n_live == 0           # nothing was mutated
    slot = eng.publish_adapter(payload, name="t", force=True)
    assert eng.adapter_pool.is_live(slot)
    while eng.has_work:
        eng.step()


def test_pool_exhaustion_raises(llama, wrapped):
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=8, max_len=32,
                      max_adapters=3, adapter_rank=RANK)
    a = eng.publish_adapter(_adapter(wrapped, 1), name="a")
    b = eng.publish_adapter(_adapter(wrapped, 2), name="b")
    # both tenants referenced -> a third insert has nowhere to land
    eng.adapter_pool.retain(a)
    eng.adapter_pool.retain(b)
    with pytest.raises(RuntimeError, match="exhausted"):
        eng.publish_adapter(_adapter(wrapped, 3), name="c")
    eng.adapter_pool.release(a)
    # idle tenant a gets LRU-recycled now
    c = eng.publish_adapter(_adapter(wrapped, 3), name="c")
    assert c == a
    assert eng.adapter_pool.stats["lru_evictions"] == 1
    eng.adapter_pool.release(b)


# ---------------------------------------------------------------------------
# disaggregated pair
# ---------------------------------------------------------------------------

@pytest.mark.disagg
def test_disagg_adapters_end_to_end(llama, wrapped):
    from distributed_training_guide_tpu.serve.disagg import DisaggEngine

    bundle, params = llama
    payload = _adapter(wrapped, 7)
    eng = DisaggEngine(bundle, params, n_slots=2, n_prefill_slots=1,
                       page_size=8, max_len=48, max_adapters=4,
                       adapter_rank=RANK)
    slot = eng.publish_adapter(payload, name="t")
    specs = [dict(s, adapter_id=slot) for s in MIXED_SPECS]
    got = _tokens(eng, specs)
    merged = merge_lora(wrapped, {"base": params, "lora": payload})
    ref = _tokens(ServeEngine(bundle, merged, n_slots=2, page_size=8,
                              max_len=48), MIXED_SPECS)
    assert got == ref
    s = eng.stats()
    assert s["adapters_live"] == 1 and s["adapter_publishes"] == 1
    assert s["adapter_requests"] == {slot: 2}
    assert eng.adapter_pool.refcount(slot) == 0   # handoff net-neutral
    assert eng.adapter_report()["max_adapters"] == 4
    eng.close()


# ---------------------------------------------------------------------------
# fleet + post-training publish
# ---------------------------------------------------------------------------

def test_post_trained_adapter_publishes_to_fleet(llama):
    """The post seam end to end: TRAIN a toy adapter (masked optimizer,
    base frozen), publish it into a 2-replica fleet as a pool insert,
    and the fleet's tenant decode matches a dedicated merged engine.
    A busy replica refuses the WHOLE publish (all-or-nothing)."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan
    from distributed_training_guide_tpu.post.loop import (
        adapter_payload, publish_trained_adapter)
    from distributed_training_guide_tpu.serve.router import local_fleet
    from distributed_training_guide_tpu.train import Trainer, adamw_cosine

    bundle, _ = llama
    wrapped4 = lora_bundle(bundle, rank=RANK)
    trainer = Trainer(bundle=wrapped4,
                      optimizer=mask_optimizer(adamw_cosine(1e-2)),
                      plan=make_plan("single",
                                     make_mesh(devices=jax.devices()[:1])),
                      donate=False)
    state = trainer.init_state(0)
    batch = {k: jnp.asarray(np.random.RandomState(0)
                            .randint(0, 64, (2, 16)))
             for k in ("input_ids", "labels")}
    for _ in range(2):
        state, metrics = trainer.step_fn(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    payload = adapter_payload(state.params)
    assert any(np.abs(np.asarray(leaf)).max() > 0
               for leaf in jax.tree.leaves(payload)), "adapter untrained"

    base_params = state.params["base"]
    fleet = local_fleet(bundle, base_params, n_replicas=2, n_slots=2,
                        page_size=8, max_len=48, max_adapters=4,
                        adapter_rank=RANK)
    slot = publish_trained_adapter(fleet, state, name="tenant")
    specs = [dict(prompt_ids=[3, 5, 7, 11], max_new_tokens=8, seed=0,
                  adapter_id=slot)]
    got = _tokens(fleet, specs)
    merged = merge_lora(wrapped4, state.params)
    ref = _tokens(ServeEngine(bundle, merged, n_slots=2, page_size=8,
                              max_len=48),
                  [dict(specs[0], adapter_id=0)])
    assert got == ref
    s = fleet.stats()
    assert s["adapters_live"] == 1                # shared pool, counted once
    assert s["adapter_requests"].get(slot) == 1

    # busy replica -> the whole publish refuses, pool untouched
    fleet.submit(Request(prompt_ids=[4, 6], max_new_tokens=16))
    fleet.step()
    inserts_before = fleet.stats()["adapter_inserts"]
    with pytest.raises(RuntimeError, match="in-flight"):
        publish_trained_adapter(fleet, state, name="again")
    assert fleet.stats()["adapter_inserts"] == inserts_before
    while fleet.has_work:
        fleet.step()
    fleet.close()


def test_adapter_payload_requires_lora_state():
    from distributed_training_guide_tpu.post.loop import adapter_payload

    with pytest.raises(ValueError, match="lora"):
        adapter_payload({"wte": np.zeros(3)})


# ---------------------------------------------------------------------------
# loadgen profile
# ---------------------------------------------------------------------------

def test_zipf_adapter_mix_scenario():
    from distributed_training_guide_tpu.serve.loadgen import (
        adapter_mix_scenario, zipf_weights)

    w = zipf_weights(4, 1.1)
    assert pytest.approx(sum(w)) == 1.0
    assert w == sorted(w, reverse=True)           # rank 1 hottest
    with pytest.raises(ValueError):
        zipf_weights(0)

    scen = adapter_mix_scenario(max_len=64, n_adapters=4,
                                base_share=0.25)
    assert scen.adapter_ids == (0, 1, 2, 3, 4)
    assert pytest.approx(sum(scen.adapter_weights)) == 1.0
    assert scen.adapter_weights[0] == 0.25
    import random as random_mod
    rng = random_mod.Random(0)
    drawn = [scen.sample(rng, vocab=64, index=i).adapter_id
             for i in range(300)]
    counts = {a: drawn.count(a) for a in set(drawn)}
    assert set(counts) <= {0, 1, 2, 3, 4}
    assert counts[1] > counts[4]                  # Zipf head beats tail
    # determinism: the same seed replays the same tenancy
    rng2 = random_mod.Random(0)
    assert drawn == [scen.sample(rng2, vocab=64, index=i).adapter_id
                     for i in range(300)]


# ---------------------------------------------------------------------------
# sharded grid
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_multilora_tp2_matches_single_device(llama, wrapped,
                                             eight_devices):
    """The pooled decode on a tp=2 mesh (sharded KV pool, replicated
    adapter stacks) is token-identical to the single-device engine for
    mixed tenant traffic."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    bundle, params = llama
    payload = _adapter(wrapped, 7)
    kw = dict(n_slots=2, page_size=8, max_len=48, max_adapters=4,
              adapter_rank=RANK)
    single = ServeEngine(bundle, params, **kw)
    slot = single.publish_adapter(payload, name="t")
    specs = [dict(MIXED_SPECS[0], adapter_id=slot), MIXED_SPECS[1]]
    want = _tokens(single, specs)

    plan = make_plan("tp", make_mesh(tp=2, devices=eight_devices[:2]))
    sharded = ServeEngine(bundle, params, plan=plan, shard_kv=True, **kw)
    assert sharded.publish_adapter(payload, name="t") == slot
    assert _tokens(sharded, specs) == want
