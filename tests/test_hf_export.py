"""HF export round-trip: torch ckpt -> native convert -> export -> torch
reload; logits must survive both directions.

This pins every inverse layout map in ``models/hf_export.py`` against the
forward maps in ``models/hf_convert.py``: a transpose, interleave, or
unstack error on ANY leaf shows up as a logits mismatch when transformers
reloads the exported checkpoint."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.models.hf_convert import (
    convert_hf_checkpoint, load_pretrained)
from distributed_training_guide_tpu.models.hf_export import export_hf_checkpoint
from distributed_training_guide_tpu.parallel import make_mesh, make_plan


def _roundtrip(tmp_path, hf_model, bundle, vocab):
    """hf save -> convert -> native load -> export -> AutoModel reload;
    assert the reloaded torch logits match the ORIGINAL torch logits."""
    hf_model.save_pretrained(tmp_path / "hf", safe_serialization=True)
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    shapes = jax.eval_shape(lambda: bundle.init(bundle.config, jax.random.key(0)))
    shardings = plan.param_shardings(bundle.param_logical_axes(bundle.config),
                                    shapes)
    params = load_pretrained(bundle, shardings, tmp_path / "conv")

    export_hf_checkpoint(bundle, params, tmp_path / "exported")
    reloaded = transformers.AutoModelForCausalLM.from_pretrained(
        tmp_path / "exported").eval()

    ids = torch.tensor(np.random.RandomState(0).randint(0, vocab, (2, 16)))
    with torch.no_grad():
        orig = hf_model(ids).logits.float().numpy()
        back = reloaded(ids).logits.float().numpy()
    np.testing.assert_allclose(back, orig, rtol=1e-5, atol=1e-5)


def test_export_llama_roundtrip(tmp_path):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    bundle = get_model("llama-debug", vocab_size=128, dtype=jnp.float32)
    _roundtrip(tmp_path, model, bundle, 128)


def test_export_qwen_bias_roundtrip(tmp_path):
    """The llama emitter's optional QKV-bias rows (Qwen2 layout)."""
    hf_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    bundle = get_model("qwen2.5-0.5b", vocab_size=128, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, max_position_embeddings=256,
                       rope_theta=10000.0, tie_word_embeddings=False,
                       dtype=jnp.float32)
    _roundtrip(tmp_path, model, bundle, 128)


def test_export_qwen3_qk_norm_roundtrip(tmp_path):
    """The llama emitter's q_norm/k_norm leaves + the qk_norm -> Qwen3 arch
    selection (randomized norm scales so identity can't mask a drop)."""
    hf_cfg = transformers.Qwen3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=256, rope_theta=10000.0,
        rms_norm_eps=1e-6, tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.Qwen3ForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for layer in model.model.layers:
            layer.self_attn.q_norm.weight.normal_(1.0, 0.3)
            layer.self_attn.k_norm.weight.normal_(1.0, 0.3)
    bundle = get_model("qwen3-0.6b", vocab_size=128, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, head_dim=32,
                       max_position_embeddings=256, rope_theta=10000.0,
                       rms_norm_eps=1e-6, tie_word_embeddings=False,
                       dtype=jnp.float32)
    _roundtrip(tmp_path, model, bundle, 128)


def test_export_gemma2_sandwich_roundtrip(tmp_path):
    """The Gemma-2 emitter: four norms per layer (post_attn_norm re-mapped
    to pre_feedforward_layernorm), softcaps/scale/layer_types in the
    config, arch selected from sandwich_norm — through AutoModel reload."""
    hf_cfg = transformers.Gemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=256, rope_theta=10000.0,
        rms_norm_eps=1e-6, query_pre_attn_scalar=24.0,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        sliding_window=16, attn_implementation="eager",
        hidden_activation="gelu_pytorch_tanh", tie_word_embeddings=True)
    torch.manual_seed(0)
    model = transformers.Gemma2ForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for layer in model.model.layers:
            layer.pre_feedforward_layernorm.weight.normal_(0.0, 0.3)
            layer.post_feedforward_layernorm.weight.normal_(0.0, 0.3)
    bundle = get_model("gemma2-2b", vocab_size=128, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, head_dim=32,
                       layer_windows=(16, 0), query_pre_attn_scalar=24.0,
                       max_position_embeddings=256, rope_theta=10000.0,
                       dtype=jnp.float32)
    _roundtrip(tmp_path, model, bundle, 128)


def test_export_olmo2_post_norm_roundtrip(tmp_path):
    """The post-norm leaves (attn_out_norm/mlp_out_norm, flat q/k norms) +
    the post_norm -> Olmo2 arch selection through AutoModel reload."""
    hf_cfg = transformers.Olmo2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.Olmo2ForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for layer in model.model.layers:
            layer.post_attention_layernorm.weight.normal_(1.0, 0.3)
            layer.post_feedforward_layernorm.weight.normal_(1.0, 0.3)
    bundle = get_model("olmo2-7b", vocab_size=128, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, max_position_embeddings=256,
                       rope_theta=10000.0, rms_norm_eps=1e-6,
                       dtype=jnp.float32)
    _roundtrip(tmp_path, model, bundle, 128)


def test_export_tied_llama_roundtrip(tmp_path):
    """tie_word_embeddings=True: the emitter must OMIT lm_head (HF re-ties
    from the embedding) and the reloaded logits still match."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=True)
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    bundle = get_model("llama-debug", vocab_size=128,
                       tie_word_embeddings=True, dtype=jnp.float32)
    _roundtrip(tmp_path, model, bundle, 128)


def test_export_gemma_roundtrip(tmp_path):
    """The Gemma config inversion ((1+w) norms, scaled embeddings, MQA,
    explicit head_dim, forced tie) through transformers reload."""
    hf_cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=32, max_position_embeddings=256, rms_norm_eps=1e-6,
        hidden_act="gelu_pytorch_tanh", tie_word_embeddings=True)
    torch.manual_seed(0)
    model = transformers.GemmaForCausalLM(hf_cfg).eval()
    bundle = get_model("gemma-2b", vocab_size=128, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=1, head_dim=32,
                       max_position_embeddings=256, dtype=jnp.float32)
    _roundtrip(tmp_path, model, bundle, 128)


def test_export_gpt2_roundtrip(tmp_path):
    hf_cfg = transformers.GPT2Config(vocab_size=160, n_embd=64, n_layer=2,
                                     n_head=4, n_positions=128)
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    bundle = get_model("gpt2-debug", vocab_size=160,
                       max_position_embeddings=128, dtype=jnp.float32)
    _roundtrip(tmp_path, model, bundle, 160)


def test_export_neox_roundtrip(tmp_path):
    """The QKV re-interleave (inverse of the conversion's de-interleave)."""
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=512, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=256, rotary_pct=0.25, hidden_act="gelu",
        use_parallel_residual=True, tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    bundle = get_model("neox-debug", dtype=jnp.float32)
    _roundtrip(tmp_path, model, bundle, 512)


def test_export_mixtral_roundtrip(tmp_path):
    """Expert-stack unstacking back to per-expert w1/w2/w3 Linears."""
    hf_cfg = transformers.MixtralConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.MixtralForCausalLM(hf_cfg).eval()
    bundle = get_model("moe-debug", dtype=jnp.float32)
    _roundtrip(tmp_path, model, bundle, 512)


def test_export_qwen3_moe_roundtrip(tmp_path):
    """The Qwen3-MoE emitter spelling (mlp.experts.N.gate_proj + mlp.gate
    router + q/k norm rows) + the qk_norm -> Qwen3Moe arch selection."""
    hf_cfg = transformers.Qwen3MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=32,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.Qwen3MoeForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for layer in model.model.layers:
            layer.self_attn.q_norm.weight.normal_(1.0, 0.3)
            layer.self_attn.k_norm.weight.normal_(1.0, 0.3)
    bundle = get_model("qwen3-30b-a3b", vocab_size=128, hidden_size=64,
                       intermediate_size=96, num_layers=2, num_heads=4,
                       num_kv_heads=2, head_dim=32, num_experts=4,
                       experts_per_token=2, norm_topk_prob=False,
                       max_position_embeddings=256, rope_theta=10000.0,
                       rms_norm_eps=1e-6, tie_word_embeddings=False,
                       capacity_factor=4.0, dtype=jnp.float32)
    _roundtrip(tmp_path, model, bundle, 128)


def test_export_qwen2_moe_shared_expert_roundtrip(tmp_path):
    """The Qwen2-MoE emitter: shared-expert leaves + the [1,E] scalar gate
    + QKV bias rows, arch selected from shared_expert_intermediate."""
    hf_cfg = transformers.Qwen2MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=96, shared_expert_intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.Qwen2MoeForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for layer in model.model.layers:
            layer.self_attn.q_proj.bias.normal_(0.0, 0.5)
            layer.mlp.shared_expert_gate.weight.normal_(0.0, 0.5)
    bundle = get_model("qwen1.5-moe-a2.7b", vocab_size=128, hidden_size=64,
                       intermediate_size=96, shared_expert_intermediate=112,
                       num_layers=2, num_heads=4, num_kv_heads=2,
                       num_experts=4, experts_per_token=2,
                       max_position_embeddings=256, rope_theta=10000.0,
                       rms_norm_eps=1e-6, capacity_factor=4.0,
                       dtype=jnp.float32)
    _roundtrip(tmp_path, model, bundle, 128)


def test_export_cli_from_orbax_checkpoint(tmp_path, eight_devices):
    """The publish workflow end to end: train a few steps through the real
    chapter loop (Orbax checkpoint), run the hf_export CLI against the
    experiment dir, reload with transformers, and match logits against the
    restored native params."""
    from distributed_training_guide_tpu.models import hf_export
    from distributed_training_guide_tpu.train.cli import get_parser, run_training

    args = get_parser().parse_args(["-m", "llama-debug"])
    args.dataset_name = "synthetic:60000"
    args.seq_length = 64
    args.batch_size = 1
    args.num_epochs = 1
    args.log_freq = 2
    args.max_steps = 3
    args.ckpt_freq = 3
    args.experiment_name = "pub"
    args.save_dir = str(tmp_path)
    out = run_training(args, lambda: make_plan("ddp", make_mesh()))

    hf_export.main(["-m", "llama-debug", "-e", str(tmp_path / "pub"),
                    "-o", str(tmp_path / "hf-out")])
    reloaded = transformers.AutoModelForCausalLM.from_pretrained(
        tmp_path / "hf-out").eval()

    bundle = get_model("llama-debug", dtype=jnp.float32)
    ids = np.random.RandomState(2).randint(0, 512, (2, 16))
    trained = jax.tree.map(lambda x: jnp.asarray(np.asarray(x), jnp.float32),
                           jax.device_get(out["state"].params))
    ours = np.asarray(bundle.apply(bundle.config, trained, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = reloaded(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_export_native_first(tmp_path):
    """The publish path: a natively-initialized (as if trained) model
    exports to a checkpoint transformers can load, and the loaded torch
    logits match our own forward."""
    bundle = get_model("llama-debug", vocab_size=128, dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(7))
    export_hf_checkpoint(bundle, params, tmp_path / "pub")
    reloaded = transformers.AutoModelForCausalLM.from_pretrained(
        tmp_path / "pub").eval()
    ids = np.random.RandomState(1).randint(0, 128, (2, 16))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = reloaded(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)
