"""Cross-feature interaction goldens: combinations of parallelism axes and
trainer options that individual test files don't cover together."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine

GB, SEQ = 8, 32


def run(strategy, mesh_kw, steps=2, sequence_sharded=None, gb=GB,
        optimizer=None, model="llama-debug", **trainer_kw):
    bundle = get_model(model, dtype=jnp.float32)
    mesh = (make_mesh(devices=jax.devices()[:1]) if strategy == "single"
            else make_mesh(**mesh_kw))
    plan = make_plan(strategy, mesh, sequence_sharded=sequence_sharded)
    t = Trainer(bundle=bundle, optimizer=optimizer or adamw_cosine(1e-3),
                plan=plan, donate=False, **trainer_kw)
    state = t.init_state(0)
    ids = np.random.RandomState(0).randint(0, 512, (gb, SEQ))
    accum = trainer_kw.get("grad_accum", 1)
    arr = ids.reshape(accum, gb // accum, SEQ) if accum > 1 else ids
    batch = {k: jax.device_put(jnp.asarray(arr), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    losses = []
    for _ in range(steps):
        state, m = t.step_fn(state, batch)
        losses.append(float(m["loss"]))
    return losses


@pytest.fixture(scope="module")
def golden():
    return run("single", {})


def test_moe_dropped_frac_metric(eight_devices):
    """MoE steps surface the routing overflow fraction as a metric."""
    bundle = get_model("moe-debug", dtype=jnp.float32)
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("ep", make_mesh(ep=2)), donate=False)
    state = t.init_state(0)
    ids = np.random.RandomState(0).randint(0, 512, (GB, SEQ))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    _, m = t.step_fn(state, batch)
    frac = float(m["moe_dropped_frac"])
    assert 0.0 <= frac <= 1.0


def test_pp_with_grad_accum(eight_devices):
    """GPipe microbatching composed with lax.scan gradient accumulation:
    accum=2 over a doubled batch must match accum=1 over the same tokens."""
    a = run("pp", {"pp": 2}, gb=16, pp_microbatches=2)
    b = run("pp", {"pp": 2}, gb=16, grad_accum=2, pp_microbatches=2)
    np.testing.assert_allclose(b, a, rtol=2e-4)


def test_cp_with_remat_and_chunked_loss(golden, eight_devices):
    losses = run("ddp", {"cp": 4}, remat=True, loss_chunks=4)
    np.testing.assert_allclose(losses, golden, rtol=2e-4)


def test_zero2_with_cp(golden, eight_devices):
    """ZeRO-2 (grads + opt state sharded over the data axes) under context
    parallelism: cp is NOT a data axis, so the reduce-scattered grad-accum
    buffer must coexist with the ring's cp-manual attention. grad_accum=2
    engages the buffer — at accum=1 the zero2 path is ZeRO-1-equivalent
    and the reduce-scatter never runs."""
    losses = run("zero2", {"cp": 2}, grad_accum=2)
    np.testing.assert_allclose(losses, golden, rtol=2e-4)


def test_ep_with_cp(eight_devices):
    """Expert parallelism x context parallelism: ep shards experts, cp
    shards the sequence through the ring, and the MoE router sees the full
    (cp-gathered-at-dispatch) token set identically on every member."""
    g = run("single", {}, model="moe-debug", attn_impl="xla")
    got = run("ep", {"ep": 2, "cp": 2}, model="moe-debug", attn_impl="xla")
    np.testing.assert_allclose(got, g, rtol=2e-4)


def test_pp_with_attn_remat_policy(golden, eight_devices):
    """The attn/attn_mlp checkpoint_name tags must survive inside the
    pipeline's per-tick jax.vjp (the policy applies between the backward
    tick's recompute and its cotangent pass)."""
    from distributed_training_guide_tpu.train.step import REMAT_POLICIES

    for policy in ("attn", "attn_mlp"):
        losses = run("pp", {"pp": 2}, remat=True, remat_policy=policy,
                     pp_microbatches=2)
        np.testing.assert_allclose(losses, golden, rtol=2e-4, err_msg=policy)
    assert {"attn", "attn_mlp"} <= set(REMAT_POLICIES)


def test_cp_with_attn_remat_policy(golden, eight_devices):
    """Under context parallelism the ring's vjp_fwd tags its output + lse
    (flash_out / flash_lse) like the flash wrappers, so the attn policy
    skips the fwd ring in backward — numerics must match, AND the backward
    jaxpr must contain fewer pallas calls than full recompute (the fwd
    ring re-running would double the ring's kernel count)."""
    losses = run("ddp", {"cp": 4}, remat=True, remat_policy="attn")
    np.testing.assert_allclose(losses, golden, rtol=2e-4)

    from distributed_training_guide_tpu.ops.ring_attention import (
        make_ring_attention)
    from distributed_training_guide_tpu.parallel import make_mesh
    from distributed_training_guide_tpu.train.step import REMAT_POLICIES

    ring = make_ring_attention(make_mesh(cp=2, devices=jax.devices()[:2]),
                               data_axes=("dp",), head_axis=None)
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (1, 32, 4, 16), jnp.float32)
    k = jax.random.normal(ks[1], (1, 32, 2, 16), jnp.float32)
    v = jax.random.normal(ks[2], (1, 32, 2, 16), jnp.float32)

    def n_pallas(policy):
        f = jax.checkpoint(
            lambda q: jnp.sum(ring(q, k, v).astype(jnp.float32) ** 2),
            policy=REMAT_POLICIES[policy])
        return str(jax.make_jaxpr(jax.grad(f))(q)).count("pallas_call")

    assert n_pallas("attn") < n_pallas("all"), \
        (n_pallas("attn"), n_pallas("all"))


def test_pp_with_host_offload(golden, eight_devices):
    """C5 x pp: optimizer state in pinned host memory while the pipeline's
    hand-differentiated schedule owns the step — the offload wrapper's
    fetch/update cycle must not perturb the trajectory."""
    losses = run("pp", {"pp": 2}, pp_microbatches=2, offload_opt_state=True)
    np.testing.assert_allclose(losses, golden, rtol=2e-4)


def test_pp_with_grad_accum_matches_single_device(golden, eight_devices):
    """C24 x pp against the SINGLE-DEVICE golden (the sibling
    test_pp_with_grad_accum compares accum=2 vs accum=1 under pp, which
    would miss a bias common to both): each accum step runs the full 1F1B
    schedule and the summed-then-averaged grads must reproduce the plain
    big-batch trajectory exactly."""
    losses = run("pp", {"pp": 2, "devices": jax.devices()[:4]},
                 pp_microbatches=2, grad_accum=2)
    np.testing.assert_allclose(losses, golden, rtol=2e-4)


def test_pp_with_adafactor(eight_devices):
    """Optimizer state for pp-sharded layer params follows the generic
    opt-state sharding machinery; adafactor's factored leaves must not
    break it."""
    from distributed_training_guide_tpu.train import adafactor_cosine

    losses = run("pp", {"pp": 2}, optimizer=adafactor_cosine(1e-2),
                 pp_microbatches=2)
    assert np.isfinite(losses).all() and losses[1] < losses[0]
