"""Context-parallel (ring attention) parity tests on the virtual mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.ops.attention import _xla_attention
from distributed_training_guide_tpu.ops.ring_attention import make_ring_attention
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine


def test_ring_attention_matches_dense(eight_devices):
    mesh = make_mesh(cp=4)
    ring = make_ring_attention(mesh)
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (2, 32, 4, 16), jnp.float32)
    k = jax.random.normal(ks[1], (2, 32, 2, 16), jnp.float32)
    v = jax.random.normal(ks[2], (2, 32, 2, 16), jnp.float32)
    ref = _xla_attention(q, k, v, causal=True, positions=None, kv_positions=None)
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_attention_grads(eight_devices):
    mesh = make_mesh(cp=4)
    ring = make_ring_attention(mesh)
    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (2, 16, 2, 8), jnp.float32)
    k = jax.random.normal(ks[1], (2, 16, 2, 8), jnp.float32)
    v = jax.random.normal(ks[2], (2, 16, 2, 8), jnp.float32)

    g1 = jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(
        _xla_attention(q, k, v, True, None, None) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_ring_output_keeps_batch_and_head_shardings(eight_devices):
    """Batch/head are manual axes of the ring shard_map: the output (and
    grads) must come back sharded over dp/tp, not replicated — the SPMD
    partitioner's gather-and-replicate fallback for the inner Pallas calls
    is exactly what the manual axes exist to prevent."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(cp=2, tp=2)  # remaining devices -> dp=2
    ring = make_ring_attention(mesh)
    ks = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(ks[0], (2, 32, 4, 16), jnp.float32)
    k = jax.random.normal(ks[1], (2, 32, 2, 16), jnp.float32)
    v = jax.random.normal(ks[2], (2, 32, 2, 16), jnp.float32)
    sh = NamedSharding(mesh, P("dp", "cp", "tp", None))
    qs = jax.device_put(q, sh)
    ks_ = jax.device_put(k, NamedSharding(mesh, P("dp", "cp", "tp", None)))
    vs = jax.device_put(v, NamedSharding(mesh, P("dp", "cp", "tp", None)))

    @jax.jit
    def f(q, k, v):
        return jax.value_and_grad(lambda q: jnp.sum(ring(q, k, v) ** 2))(q)

    loss, grad = f(qs, ks_, vs)
    ref = jax.value_and_grad(
        lambda q: jnp.sum(_xla_attention(q, k, v, True, None, None) ** 2))(q)
    np.testing.assert_allclose(float(loss), float(ref[0]), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref[1]),
                               rtol=1e-4, atol=1e-5)
    spec = grad.sharding.spec
    assert "dp" in str(spec) and "tp" in str(spec), spec


def _run_losses(bundle, plan, ids, steps=2):
    """Shared trainer-loop harness for the cp goldens below."""
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), plan=plan,
                donate=False)
    state = t.init_state(0)
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    losses = []
    for _ in range(steps):
        state, m = t.step_fn(state, batch)
        losses.append(float(m["loss"]))
    return losses


def test_cp_training_matches_single_device(eight_devices):
    bundle = get_model("llama-debug", dtype=jnp.float32)
    ids = np.random.RandomState(0).randint(0, 512, (8, 32))

    def run(plan):
        return _run_losses(bundle, plan, ids)

    golden = run(make_plan("single", make_mesh(devices=jax.devices()[:1])))
    cp = run(make_plan("ddp", make_mesh(cp=4)))
    np.testing.assert_allclose(cp, golden, rtol=2e-4)
    cp_fsdp = run(make_plan("fsdp", make_mesh(cp=2, fsdp=2)))
    np.testing.assert_allclose(cp_fsdp, golden, rtol=2e-4)
    # cp x tp: heads join the ring's manual axes (the trainer gates this on
    # the plan actually tp-sharding heads)
    cp_tp = run(make_plan("tp", make_mesh(cp=2, tp=2)))
    np.testing.assert_allclose(cp_tp, golden, rtol=2e-4)
    # 3-axis: cp x tp x fsdp on all 8 devices (the llama-3-style long-context
    # layout minus pp)
    cp_tp_fsdp = run(make_plan("tp_fsdp", make_mesh(cp=2, tp=2, fsdp=2)))
    np.testing.assert_allclose(cp_tp_fsdp, golden, rtol=2e-4)


def test_cp_neox_matches_single_device(eight_devices):
    """Ring context parallelism with the NeoX family: partial rotary takes
    the EXPLICIT per-shard positions path (each cp member holds a sequence
    slice), and the parallel-residual block feeds the ring attention as a
    callable attn_impl — trajectory must match single-device."""
    bundle = get_model("neox-debug", dtype=jnp.float32)
    ids = np.random.RandomState(1).randint(0, 512, (8, 32))

    golden = _run_losses(bundle,
                         make_plan("single", make_mesh(devices=jax.devices()[:1])),
                         ids)
    cp = _run_losses(bundle, make_plan("ddp", make_mesh(cp=4)), ids)
    np.testing.assert_allclose(cp, golden, rtol=2e-4)


def test_ulysses_attention_matches_dense(eight_devices):
    """Both Ulysses paths (constraint-based xla, manual-axes flash) against
    the dense reference; kv heads divide cp x tp so the flash path engages."""
    from distributed_training_guide_tpu.ops.ulysses_attention import (
        make_ulysses_attention)

    mesh = make_mesh(cp=2, tp=2)  # remaining devices -> dp=2
    ks = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(ks[0], (2, 32, 8, 16), jnp.float32)
    k = jax.random.normal(ks[1], (2, 32, 4, 16), jnp.float32)
    v = jax.random.normal(ks[2], (2, 32, 4, 16), jnp.float32)
    ref = jax.value_and_grad(
        lambda q: jnp.sum(_xla_attention(q, k, v, True, None, None) ** 2))(q)
    for impl in ("xla", "flash"):
        attn = make_ulysses_attention(mesh, impl=impl)

        @jax.jit
        def f(q, k, v, attn=attn):
            return jax.value_and_grad(
                lambda q: jnp.sum(attn(q, k, v) ** 2))(q)

        loss, grad = f(q, k, v)
        np.testing.assert_allclose(float(loss), float(ref[0]), rtol=1e-4,
                                   err_msg=impl)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref[1]),
                                   rtol=2e-4, atol=1e-4, err_msg=impl)


def test_ring_scan_hop_loop_matches_dense(eight_devices):
    """hop_loop='scan' (the default at cp >= 8) rolls the cp hops into one
    lax.scan iteration — per hop op-for-op identical to the unrolled form,
    O(1) program size. Forward AND gradients must match the dense
    reference exactly like the unrolled ring does."""
    mesh = make_mesh(cp=4, devices=jax.devices()[:4])
    ring = make_ring_attention(mesh, data_axes=("dp",), head_axis=None,
                               hop_loop="scan")
    ks = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(ks[0], (2, 32, 4, 16), jnp.float32)
    k = jax.random.normal(ks[1], (2, 32, 2, 16), jnp.float32)
    v = jax.random.normal(ks[2], (2, 32, 2, 16), jnp.float32)
    ref_o = _xla_attention(q, k, v, causal=True, positions=None,
                           kv_positions=None)
    # all three grads: dk/dv ride the ring WITH the k/v blocks and are
    # delivered by the extra per-hop rotation — the scan hop's most fragile
    # routing (a dq-only check would stay green if dk/dv went to the wrong
    # owners, since dq is computed from the resident q chunks)
    ref_g = jax.grad(lambda q, k, v: jnp.sum(
        _xla_attention(q, k, v, True, None, None) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    o = jax.jit(lambda q, k, v: ring(q, k, v))(q, k, v)
    g = jax.jit(jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2),
                         argnums=(0, 1, 2)))(q, k, v)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref_o),
                               rtol=2e-4, atol=2e-4)
    for got, ref in zip(g, ref_g):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
    with pytest.raises(ValueError, match="hop_loop"):
        make_ring_attention(mesh, hop_loop="banana")


def test_ulysses_auto_falls_back_on_gqa_indivisibility(eight_devices, monkeypatch):
    """impl='auto' on TPU resolves to flash — but a GQA model whose kv heads
    don't divide cp*tp must degrade to the constraint-based xla path instead
    of hard-erroring (consistent with 'auto' semantics elsewhere); an
    explicit impl='flash' still fails loud."""
    from distributed_training_guide_tpu.ops.ulysses_attention import (
        make_ulysses_attention)

    mesh = make_mesh(cp=2, tp=2)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")  # force 'auto'->flash
    auto_attn = make_ulysses_attention(mesh, impl="auto")
    flash_attn = make_ulysses_attention(mesh, impl="flash")
    monkeypatch.undo()

    ks = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(ks[0], (2, 32, 8, 16), jnp.float32)
    k = jax.random.normal(ks[1], (2, 32, 2, 16), jnp.float32)  # 2 % (cp*tp)=4 != 0
    v = jax.random.normal(ks[2], (2, 32, 2, 16), jnp.float32)
    ref = _xla_attention(q, k, v, True, None, None)
    out = jax.jit(auto_attn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(flash_attn)(q, k, v)


def test_ulysses_training_matches_single_device(eight_devices):
    """context_impl='ulysses' reproduces the single-device trajectory, on
    both the constraint path (auto -> xla off-TPU) and the forced-flash
    manual wrapper."""
    def run(plan=None, **kw):
        bundle = get_model("llama-debug")
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                    plan=plan, donate=False, **kw)
        state = t.init_state(0)
        ids = np.random.RandomState(7).randint(0, bundle.config.vocab_size,
                                               (4, 64))
        batch = {kk: jax.device_put(jnp.asarray(ids), t.batch_shardings()[kk])
                 for kk in ("input_ids", "labels")}
        losses = []
        for _ in range(3):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses

    golden = run(make_plan("single", make_mesh(devices=jax.devices()[:1])))
    ulysses = run(make_plan("ddp", make_mesh(cp=2)), context_impl="ulysses")
    np.testing.assert_allclose(ulysses, golden, rtol=2e-4)
    ulysses_flash = run(make_plan("ddp", make_mesh(cp=2)),
                        context_impl="ulysses", attn_impl="flash")
    np.testing.assert_allclose(ulysses_flash, golden, rtol=2e-4)
    ulysses_fsdp = run(make_plan("fsdp", make_mesh(cp=2, fsdp=2)),
                       context_impl="ulysses")
    np.testing.assert_allclose(ulysses_fsdp, golden, rtol=2e-4)


def test_ring_attention_zigzag_noncausal(eight_devices):
    # non-causal path: every chunk pair is live; relayout must still invert
    mesh = make_mesh(cp=4)
    ring = make_ring_attention(mesh, causal=False)
    ks = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(ks[0], (2, 32, 4, 16), jnp.float32)
    k = jax.random.normal(ks[1], (2, 32, 2, 16), jnp.float32)
    v = jax.random.normal(ks[2], (2, 32, 2, 16), jnp.float32)
    ref = _xla_attention(q, k, v, causal=False, positions=None, kv_positions=None)
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)
