"""Quantized KV pages (serve/kv_pages.py ``kv_dtype="int8"``): int8
payloads with per-(position, kv-head) absmax scales as first-class pool
state.

What is pinned here, and why these meters:

- ATTEND PARITY with documented error bounds: int8-vs-fp32 attention over
  the same context, across the serving feature grid (GQA, windows,
  softcap, shuffled physical layouts). The per-element quantization error
  is <= scale/2 = absmax/254 (~0.4% of each vector's absmax); for
  standard-normal k/v the observed attend error is <~1e-2 absolute — the
  grid asserts 5e-2, a ~5x margin. The INT8 flash kernel (in-tile
  dequant) must match the int8 gather path to 1e-5 — those two read the
  SAME quantized bytes, so their difference is pure kernel error, not
  quantization.
- SCALE LIFECYCLE: scales ride page identity — CoW forks copy them,
  commits/scatters write them beside their payload, the sharded pool
  splits them on the kv-head axis. A dst page with stale scales would
  dequantize garbage, which is why the fork pin checks BOTH leaves.
- BYTE + HLO PINS: the int8 pool (scales included) is <= 0.55x the fp32
  pool (0.3125x at head_dim 16: 1 payload byte + 4/16 scale bytes per
  element vs 4); the lowered decode's pool avals are int8 in AND out
  with no fp32 pool-shaped tensor anywhere in the program.
- QUALITY METER: spec-decoding acceptance is a sensitive function of KV
  fidelity (a perturbed verify logit breaks a drafted run immediately,
  long before evals would move). Acceptance under the int8 pool must be
  within 0.02 of the fp32-KV control on the lookup-friendly workload —
  the same meter bench.py's kvq_spec_accept rung records (CPU point:
  0.862 int8 vs 0.852 fp32).
- ENGINE INVARIANTS carry over because quantization is pure per token
  (one absmax scale per written vector — never a function of co-resident
  page content): batch-1 identity, spec-on == spec-off, preemption
  replay, the disaggregated handoff, and the tp=2 sharded pool are all
  re-pinned under int8. The int8 random-trace re-run lives in
  test_serve.py (parameterized).
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.ops.paged_decode import (
    paged_decode_eligible, paged_flash_decode)
from distributed_training_guide_tpu.serve.api import generate_many
from distributed_training_guide_tpu.serve.engine import ServeEngine
from distributed_training_guide_tpu.serve.kv_pages import (
    commit_prefill, copy_pages, dequantize_kv, init_pages, kv_dtype_name,
    kv_page_bytes, paged_attend, quantize_kv)
from distributed_training_guide_tpu.serve.scheduler import Request
from distributed_training_guide_tpu.train.precision import Quantized
from distributed_training_guide_tpu.utils import hlo as hlo_util

pytestmark = [pytest.mark.serve, pytest.mark.kvquant]

ATTEND_ATOL = 5e-2   # documented bound for N(0,1) k/v — see module docstring


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


def _fresh(req):
    return dataclasses.replace(req, request_id=None)


# ---- quantization grain ----------------------------------------------------

def test_quantize_kv_roundtrip_bound_and_shapes():
    """One fp32 scale per (position, kv-head) vector; round-trip error is
    bounded by scale/2 per element, scale = that vector's absmax/127."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((3, 5, 2, 16)).astype(np.float32)
    x[0, 0, 0] *= 100.0          # an outlier vector costs only ITS block
    qt = quantize_kv(jnp.asarray(x))
    assert qt.q.shape == x.shape and qt.q.dtype == jnp.int8
    assert qt.scale.shape == x.shape[:-1] + (1,)
    back = np.asarray(dequantize_kv(qt))
    amax = np.abs(x).max(axis=-1, keepdims=True)
    np.testing.assert_array_less(
        np.abs(back - x), np.broadcast_to(amax / 254 + 1e-7, x.shape))


def test_quantize_kv_is_pure_per_token():
    """The bitwise-replay foundation: a vector's quantization never
    depends on what else is in the page — re-quantizing the same value
    yields the same bytes whatever wrote it first."""
    rng = np.random.default_rng(1)
    x = rng.standard_normal((4, 2, 16)).astype(np.float32)
    a = quantize_kv(jnp.asarray(x))
    b = quantize_kv(jnp.asarray(x[1:2]))
    np.testing.assert_array_equal(np.asarray(a.q[1:2]), np.asarray(b.q))
    np.testing.assert_array_equal(np.asarray(a.scale[1:2]),
                                  np.asarray(b.scale))


def test_kv_page_bytes_int8_includes_scales():
    cfg = get_model("llama-debug", dtype=jnp.float32).config
    fp32 = kv_page_bytes(cfg, page_size=16)
    int8 = kv_page_bytes(cfg, page_size=16, kv_dtype="int8")
    # per (position, head): head_size payload bytes + 4 scale bytes
    expect = (cfg.num_layers * 2 * 16 * cfg.num_kv_heads
              * (cfg.head_size + 4))
    assert int8 == expect
    assert int8 / fp32 <= 0.55            # the acceptance-criteria pin
    assert kv_dtype_name(cfg, None) == "fp32"
    with pytest.raises(ValueError, match="kv_dtype"):
        kv_dtype_name(cfg, "fp8")


def test_int8_pool_real_nbytes_vs_fp32():
    """The device arrays themselves (payload + scales summed) obey the
    same <= 0.55x pin as the formula — the formula can't silently drift
    from what is actually resident."""
    cfg = get_model("llama-debug", dtype=jnp.float32).config
    p8 = init_pages(cfg, 6, 8, kv_dtype="int8")
    p32 = init_pages(cfg, 6, 8)
    nb8 = sum(x.nbytes for x in jax.tree.leaves(p8))
    nb32 = sum(x.nbytes for x in jax.tree.leaves(p32))
    assert nb8 / nb32 <= 0.55
    assert nb8 == kv_page_bytes(cfg, page_size=8, n_pages=6,
                                kv_dtype="int8")
    assert isinstance(p8["k"], Quantized)
    assert p8["k"].q.dtype == jnp.int8
    assert p8["k"].scale.dtype == jnp.float32


# ---- attend parity grid ----------------------------------------------------

def _paged_state(rng, *, s, m, page, n_pages, hkv, d, lengths):
    """Shuffled physical layout with a filled history, fp32 + int8 twins."""
    phys = rng.permutation(np.arange(1, n_pages))
    tables = np.zeros((s, m), np.int32)
    for i in range(s):
        tables[i] = phys[i * m:(i + 1) * m]
    kp = np.zeros((n_pages, page, hkv, d), np.float32)
    vp = np.zeros((n_pages, page, hkv, d), np.float32)
    ctx = rng.standard_normal((s, m * page, hkv, d)).astype(np.float32)
    vctx = rng.standard_normal((s, m * page, hkv, d)).astype(np.float32)
    for i in range(s):
        for t in range(int(lengths[i])):
            kp[tables[i, t // page], t % page] = ctx[i, t]
            vp[tables[i, t // page], t % page] = vctx[i, t]
    return tables, kp, vp


GRID = [
    dict(),                                    # plain causal
    dict(window=5),                            # SWA across pages
    dict(softcap=20.0),                        # Gemma-2 softcap
    dict(window=8, scale=0.25, softcap=50.0),  # full Gemma-2 decode
]


@pytest.mark.parametrize("hq,hkv", [(4, 2), (8, 1)])
@pytest.mark.parametrize("kw", GRID, ids=lambda kw: "-".join(kw) or "causal")
def test_int8_attend_parity_vs_fp32(hq, hkv, kw):
    """int8 gather attend vs the fp32 gather attend over the same context
    stays inside the documented quantization bound across the feature
    grid and shuffled layouts; the scatter writes quantized bytes +
    scales at the same (page, offset) the fp32 path writes."""
    rng = np.random.default_rng(3)
    s, m, page, n_pages, d = 3, 4, 4, 16, 16
    lengths = np.array([5, 0, 11], np.int32)
    tables, kp, vp = _paged_state(rng, s=s, m=m, page=page, n_pages=n_pages,
                                  hkv=hkv, d=d, lengths=lengths)
    q = rng.standard_normal((s, 1, hq, d)).astype(np.float32)
    k_new = rng.standard_normal((s, 1, hkv, d)).astype(np.float32)
    v_new = rng.standard_normal((s, 1, hkv, d)).astype(np.float32)
    out32, _ = paged_attend(
        jnp.asarray(q), jnp.asarray(k_new), jnp.asarray(v_new),
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tables),
        jnp.asarray(lengths), **kw)
    out8, (nkp, nvp) = paged_attend(
        jnp.asarray(q), jnp.asarray(k_new), jnp.asarray(v_new),
        quantize_kv(jnp.asarray(kp)), quantize_kv(jnp.asarray(vp)),
        jnp.asarray(tables), jnp.asarray(lengths), **kw)
    assert float(jnp.max(jnp.abs(out32 - out8))) < ATTEND_ATOL
    # the new token's quantized write landed beside its scale
    i, n = 0, int(lengths[0])
    want = quantize_kv(jnp.asarray(k_new))[0][i, 0]
    np.testing.assert_array_equal(
        np.asarray(nkp.q[tables[i, n // page], n % page]), np.asarray(want))


def test_int8_flash_kernel_matches_int8_gather():
    """The in-kernel dequant reads the SAME quantized bytes as the gather
    dequant — parity at 1e-5 is kernel correctness, quantization error
    cancels. Grid includes window/scale/softcap and zero-length slots."""
    rng = np.random.default_rng(4)
    s, m, page, n_pages, hq, hkv, d = 4, 4, 4, 20, 4, 2, 16
    lengths = np.array([4, 0, 9, 15], np.int32)
    tables, kp, vp = _paged_state(rng, s=s, m=m, page=page, n_pages=n_pages,
                                  hkv=hkv, d=d, lengths=lengths)
    kq, vq = quantize_kv(jnp.asarray(kp)), quantize_kv(jnp.asarray(vp))
    q = rng.standard_normal((s, 1, hq, d)).astype(np.float32)
    k_new = rng.standard_normal((s, 1, hkv, d)).astype(np.float32)
    v_new = rng.standard_normal((s, 1, hkv, d)).astype(np.float32)
    for kw in (dict(), dict(window=6, scale=0.3, softcap=30.0)):
        outs = {}
        for impl in ("flash", "xla"):
            attn, (nkp, nvp) = paged_attend(
                jnp.asarray(q), jnp.asarray(k_new), jnp.asarray(v_new),
                kq, vq, jnp.asarray(tables), jnp.asarray(lengths),
                impl=impl, **kw)
            outs[impl] = (np.asarray(attn), np.asarray(nkp.q),
                          np.asarray(nkp.scale))
        np.testing.assert_allclose(outs["flash"][0], outs["xla"][0],
                                   rtol=1e-5, atol=1e-5)
        # the quantized scatter is shared: payload AND scales bitwise
        np.testing.assert_array_equal(outs["flash"][1], outs["xla"][1])
        np.testing.assert_array_equal(outs["flash"][2], outs["xla"][2])
        # and against the fp32 XLA reference the int8 KERNEL stays inside
        # the documented quantization bound (the acceptance-criteria pin)
        ref32, _ = paged_attend(
            jnp.asarray(q), jnp.asarray(k_new), jnp.asarray(v_new),
            jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tables),
            jnp.asarray(lengths), impl="xla", **kw)
        assert float(np.max(np.abs(outs["flash"][0]
                                   - np.asarray(ref32)))) < ATTEND_ATOL


def test_int8_flash_ineligible_page_size_warns_at_construction(llama):
    """An int8 pool whose page_size can't take the compiled kernel's
    int8 tiles (page % 32) must say so when the engine is BUILT — on TPU
    'auto' would otherwise silently run the ~3x-traffic gather path at
    the default page_size=16, contradicting the in-kernel-dequant pitch.
    It fires only when int8 REGRESSED eligibility: a head_dim the fp32
    kernel couldn't tile either (the debug models) never had flash to
    lose, and an explicit attend_impl='xla' is a gather choice."""
    import warnings

    from distributed_training_guide_tpu.serve.kv_pages import \
        check_kv_page_geometry

    big = type("C", (), {"head_size": 128, "num_heads": 8,
                         "dtype": jnp.float32})()
    with pytest.warns(UserWarning, match="page_size % 32"):
        check_kv_page_geometry(big, page_size=16, kv_dtype="int8",
                               attend_impl="auto")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        # eligible page, explicit gather, fp32 pool: all silent
        check_kv_page_geometry(big, page_size=32, kv_dtype="int8",
                               attend_impl="auto")
        check_kv_page_geometry(big, page_size=16, kv_dtype="int8",
                               attend_impl="xla")
        check_kv_page_geometry(big, page_size=16, kv_dtype=None,
                               attend_impl="auto")
        # and through the engine: llama-debug's head_dim 16 never had the
        # compiled kernel, so its int8 engines build without noise
        bundle, params = llama
        ServeEngine(bundle, params, n_slots=1, page_size=16, max_len=64,
                    kv_dtype="int8")


def test_paged_flash_decode_scale_validation_and_eligibility():
    kq = jnp.zeros((4, 4, 2, 16), jnp.int8)
    with pytest.raises(ValueError, match="half-quantized"):
        paged_flash_decode(jnp.zeros((1, 4, 16)), kq, kq,
                           jnp.zeros((1, 2), jnp.int32),
                           jnp.zeros(1, jnp.int32),
                           k_scale=jnp.zeros((4, 4, 2)), interpret=True)
    # int8 compiled tiles are stricter on the sublane (page) axis
    assert paged_decode_eligible(64, 32, quantized=True)
    assert not paged_decode_eligible(64, 16, quantized=True)
    assert paged_decode_eligible(64, 16, quantized=False)


# ---- scale lifecycle -------------------------------------------------------

def test_commit_prefill_int8_writes_scales_and_respects_start():
    """The bucket-commit write site: quantized payload + scales land at
    the same (page, offset); ``start`` (shared-prefix territory) and the
    pad tail route to the trash page for BOTH leaves."""
    layers, page, n_pages, h, d = 2, 4, 8, 2, 16
    rng = np.random.default_rng(5)
    pool = init_pages(
        type("C", (), {"num_layers": layers, "num_heads": h,
                       "head_size": d, "dtype": jnp.float32})(),
        n_pages, page, kv_dtype="int8")
    k_pages, v_pages = pool["k"], pool["v"]
    marker_q = k_pages.q.at[:, 5].set(7)
    marker_s = k_pages.scale.at[:, 5].set(3.0)
    k_pages = Quantized(marker_q, marker_s)
    k_dense = rng.standard_normal((layers, 8, h, d)).astype(np.float32)
    v_dense = rng.standard_normal((layers, 8, h, d)).astype(np.float32)
    table_row = jnp.asarray([5, 3, 0, 0], jnp.int32)
    nkp, nvp = jax.jit(commit_prefill)(
        k_pages, v_pages, jnp.asarray(k_dense), jnp.asarray(v_dense),
        table_row, jnp.asarray(6), jnp.asarray(4))
    want = quantize_kv(jnp.asarray(k_dense))
    # the shared page (positions < start) is untouched in BOTH leaves
    np.testing.assert_array_equal(np.asarray(nkp.q[:, 5]),
                                  np.full((layers, page, h, d), 7, np.int8))
    np.testing.assert_array_equal(np.asarray(nkp.scale[:, 5]),
                                  np.full((layers, page, h, 1), 3.0))
    for t in (4, 5):                                   # committed tokens
        np.testing.assert_array_equal(
            np.asarray(nkp.q[:, 3, t % page]), np.asarray(want.q[:, t]))
        np.testing.assert_array_equal(
            np.asarray(nkp.scale[:, 3, t % page]),
            np.asarray(want.scale[:, t]))


def test_cow_fork_copies_scales():
    """The CoW pin: copy_pages on a quantized pool duplicates payload AND
    scale rows — a forked page that kept the old scales would dequantize
    garbage the moment the fork diverges."""
    rng = np.random.default_rng(6)
    pool = Quantized(
        q=jnp.asarray(rng.integers(-127, 127, (2, 6, 4, 2, 16)), jnp.int8),
        scale=jnp.asarray(rng.uniform(0.01, 2.0, (2, 6, 4, 2, 1)),
                          jnp.float32))
    vpool = Quantized(q=pool.q + 1, scale=pool.scale * 2)
    nkp, nvp = jax.jit(copy_pages)(pool, vpool, jnp.asarray(3),
                                   jnp.asarray(5))
    for got, src in ((nkp, pool), (nvp, vpool)):
        np.testing.assert_array_equal(np.asarray(got.q[:, 5]),
                                      np.asarray(src.q[:, 3]))
        np.testing.assert_array_equal(np.asarray(got.scale[:, 5]),
                                      np.asarray(src.scale[:, 3]))
        others = [0, 1, 2, 4]
        np.testing.assert_array_equal(np.asarray(got.q[:, others]),
                                      np.asarray(src.q[:, others]))


# ---- engine-level pins -----------------------------------------------------

def test_int8_engine_batch1_identity_and_stats(llama):
    """Scheduling invariance carries into the quantized world: co-batched
    int8 completions equal their int8 batch-1 runs token for token, and
    the byte lever is visible on stats()/kv_report."""
    bundle, params = llama
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=8,
                    temperature=0.9 if i % 2 else 0.0, seed=i)
            for i in range(4)]
    eng = ServeEngine(bundle, params, n_slots=4, page_size=4, max_len=32,
                      kv_dtype="int8")
    res = generate_many(eng, reqs)
    ref = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=32,
                      kv_dtype="int8")
    for r, req in zip(res, reqs):
        assert r.token_ids == generate_many(ref, [_fresh(req)])[0].token_ids
    st = eng.stats()
    assert st["pool_dtype"] == "int8"
    assert st["bytes_per_page"] == kv_page_bytes(bundle.config, page_size=4,
                                                 kv_dtype="int8")
    rep = eng.kv_report()
    assert rep["pool_dtype"] == "int8"
    assert rep["bytes_vs_fp32"] <= 0.55
    assert rep["pool_bytes"] == eng.kv_cache_bytes()
    fp32_eng = ServeEngine(bundle, params, n_slots=4, page_size=4,
                           max_len=32)
    assert eng.kv_cache_bytes() / fp32_eng.kv_cache_bytes() <= 0.55


def test_int8_decode_hlo_pool_avals_are_int8(llama):
    """The lowered decode's only pool-shaped tensors are int8: payload in
    and out as s8, scales as small f32 rows, and NO fp32 tensor of the
    pool's 5-d shape anywhere — the program never materializes a
    dequantized pool (the gather transient is [S, M*page, ...], a
    different shape by construction)."""
    bundle, params = llama
    cfg = bundle.config
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                      kv_dtype="int8")
    arr = eng.scheduler.decode_arrays()
    lowered = eng._decode_fn.lower(
        eng.params, eng.pages["k"], eng.pages["v"],
        jnp.asarray(arr["tokens"]), jnp.asarray(arr["lengths"]),
        jnp.asarray(arr["tables"]), jnp.asarray(arr["seeds"]),
        jnp.asarray(arr["temps"]), jnp.asarray(arr["top_ks"]),
        jnp.asarray(arr["top_ps"]), jnp.asarray(arr["actives"]))
    text = lowered.as_text()
    pool_shape = (cfg.num_layers, eng.scheduler.pool.n_pages, 4,
                  cfg.num_kv_heads, cfg.head_size)
    assert (hlo_util.has_aval(text, "i8", pool_shape)      # StableHLO
            or hlo_util.has_aval(text, "s8", pool_shape)), \
        "int8 pool aval missing from the lowered decode"
    assert not hlo_util.has_aval(text, "f32", pool_shape), \
        "a full fp32 pool-shaped tensor appears in the int8 decode"
    # and the engine's resident pages really are int8 + f32 scales
    assert eng.pages["k"].q.dtype == jnp.int8
    assert eng.pages["k"].scale.shape == pool_shape[:-1] + (1,)


def test_int8_spec_identity_and_acceptance_meter(llama):
    """(a) spec-on == spec-off under the int8 pool (the verify forward
    reads the same quantized pages as plain decode, and quantize-at-write
    is deterministic per token); (b) THE quality meter: acceptance on the
    lookup-friendly workload within 0.02 of the fp32-KV control."""
    bundle, params = llama
    block = [7, 11, 13, 17, 19, 23, 29, 31]
    prompt = (block * 6)[:48]
    reqs = [Request(prompt_ids=prompt + [40 + i], max_new_tokens=48,
                    seed=i) for i in range(4)]

    def run(kv_dtype, speculate):
        eng = ServeEngine(bundle, params, n_slots=4, page_size=8,
                          max_len=128, kv_dtype=kv_dtype,
                          speculate=speculate, spec_k=6)
        res = generate_many(eng, [_fresh(r) for r in reqs])
        # .get: the key is OMITTED when nothing was drafted (spec off)
        return [r.token_ids for r in res], \
            eng.stats().get("spec_acceptance_rate", 0.0)

    toks_on, acc8 = run("int8", "ngram")
    toks_off, _ = run("int8", None)
    assert toks_on == toks_off, "spec-on != spec-off under int8 KV"
    _, acc32 = run(None, "ngram")
    assert acc8 > 0.0
    assert abs(acc8 - acc32) <= 0.02, \
        f"int8 KV moved spec acceptance by {acc8 - acc32:+.3f}"


def test_int8_prefix_share_and_preemption_pressure(llama):
    """CoW + prefix sharing + preemption-by-recompute on a TIGHT int8
    pool: completions stay token-identical to batch-1 (the replay rewrite
    re-quantizes the same values to the same bytes)."""
    bundle, params = llama
    prefix = [9, 9, 9, 9, 5, 6, 7, 8]
    reqs = [Request(prompt_ids=prefix + [20 + i], max_new_tokens=6, seed=i)
            for i in range(4)]
    eng = ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=24,
                      n_pages=12, prefill_chunk=4, kv_dtype="int8")
    res = generate_many(eng, reqs)
    assert eng.scheduler.stats["prefix_hits"] > 0
    # same prefill MODE as the engine under test: under int8 the chunk
    # and bucket programs write measurably different caches (chunked
    # prompts attend over already-quantized history), so identity is
    # program-relative — see serve/kv_pages.py docstring
    ref = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=24,
                      prefill_chunk=4, prefix_cache=False, kv_dtype="int8")
    for r, req in zip(res, reqs):
        assert r.token_ids == generate_many(ref, [_fresh(req)])[0].token_ids


def test_int8_disagg_handoff_moves_scales_for_free(llama):
    """The disaggregated pair over one int8 pool: page-id handoff moves
    payload AND scales by refcount (bytes_copied stays 0), and the pair
    equals the int8 monolith token for token."""
    from distributed_training_guide_tpu.serve.disagg import DisaggEngine

    bundle, params = llama
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=6, seed=i)
            for i in range(3)]
    pair = DisaggEngine(bundle, params, n_slots=2, n_prefill_slots=1,
                        page_size=4, max_len=32, kv_dtype="int8")
    res = generate_many(pair, [_fresh(r) for r in reqs])
    mono = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32,
                       kv_dtype="int8")
    ref = generate_many(mono, [_fresh(r) for r in reqs])
    assert [r.token_ids for r in res] == [r.token_ids for r in ref]
    st = pair.stats()
    assert st["handoff_transfers"] > 0 and st["handoff_bytes_copied"] == 0
    assert st["pool_dtype"] == "int8"


def test_int8_sharded_pool_tp2(llama, eight_devices):
    """kv-head-sharded int8 pool (tp=2): token-identical to the
    replicated int8 engine, with each chip holding kvh/2 heads of payload
    AND scales — the per-(position, head) scale grain is what keeps the
    manual region collective-free."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    bundle, params = llama
    cfg = bundle.config
    plan = make_plan("tp", make_mesh(tp=2, devices=eight_devices[:2]))
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=6, seed=i)
            for i in range(3)]
    eng = ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=32,
                      plan=plan, shard_kv=True, kv_dtype="int8")
    res = generate_many(eng, [_fresh(r) for r in reqs])
    repl = ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=32,
                       kv_dtype="int8")
    ref = generate_many(repl, [_fresh(r) for r in reqs])
    assert [r.token_ids for r in res] == [r.token_ids for r in ref]
    for leaf, trailing in ((eng.pages["k"].q, cfg.head_size),
                           (eng.pages["k"].scale, 1)):
        shard = leaf.addressable_shards[0].data
        assert shard.shape[3] == cfg.num_kv_heads // 2
        assert shard.shape[4] == trailing
