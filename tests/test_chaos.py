"""Chaos drills: deterministic fault injection through the REAL entry points
(ISSUE 1 acceptance criteria).

- SIGKILL a supervised training run at step N; the supervisor restarts it,
  the restart resumes from the last checkpoint, and the stitched loss
  trajectory equals an uninterrupted golden run.
- Corrupt the latest checkpoint after a run; the next resume falls back to
  the previous valid checkpoint via the manifest chain and continues with
  the golden trajectory from there.
- Inject a NaN loss at a chosen step; the `skip` guard policy drops exactly
  that update and finishes, the `abort` policy dies with a machine-readable
  error file naming the step.

Subprocess drills share the multi-process suite's persistent compile cache
and are individually time-bounded; the faults themselves are the env-var
switches documented in ``diagnosing-errors/README.md`` ("Failure drills"),
so these tests are also executable documentation.
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from distributed_training_guide_tpu.utils import faults

REPO = Path(__file__).parent.parent
CH02 = REPO / "02-distributed-data-parallel" / "train_llm.py"

pytestmark = pytest.mark.chaos

# shared with tests/test_multiprocess.py so compiles amortize across suites
MP_COMPILE_CACHE = os.path.join(
    os.environ.get("TMPDIR", "/tmp"), "dtg_tpu_mp_compile_cache")

TRAIN_FLAGS = ["-m", "llama-debug", "-d", "synthetic:60000", "-s", "64",
               "-b", "1", "--num-epochs", "2", "--log-freq", "1"]


def _env(**extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               JAX_COMPILATION_CACHE_DIR=MP_COMPILE_CACHE)
    env.update(extra)
    return env


def losses_by_step(text: str) -> dict:
    import ast

    out = {}
    for line in text.splitlines():
        at = line.find("INFO:{")
        if at >= 0:
            try:
                d = ast.literal_eval(line[at + 5:])
            except (ValueError, SyntaxError):
                continue
            if isinstance(d, dict) and "global_step" in d:
                out[d["global_step"]] = d["running_loss"]
    return out


def run_ch02(flags, *, env_extra=None, timeout=420):
    os.makedirs(MP_COMPILE_CACHE, exist_ok=True)
    proc = subprocess.run([sys.executable, str(CH02), *TRAIN_FLAGS, *flags],
                          capture_output=True, text=True, timeout=timeout,
                          cwd=REPO, env=_env(**(env_extra or {})))
    return proc.returncode, proc.stdout + proc.stderr


def test_sigkill_restart_resume_matches_uninterrupted(tmp_path):
    """The headline drill: DTG_FAULT_CRASH_STEP SIGKILLs the worker right
    after the step-4 checkpoint publishes; the supervisor restarts it; the
    restart resumes from checkpoint-4 and finishes steps 5-6. The stitched
    per-step losses must EQUAL (not approximate) the uninterrupted run's."""
    rc, golden_text = run_ch02(["--max-steps", "6",
                                "--save-dir", str(tmp_path / "golden")])
    assert rc == 0, golden_text[-3000:]
    golden = losses_by_step(golden_text)
    assert set(golden) == {1, 2, 3, 4, 5, 6}

    work = tmp_path / "work"
    sup_logs = tmp_path / "sup"
    cmd = [sys.executable, "-m",
           "distributed_training_guide_tpu.launch.supervisor",
           "--max-restarts", "2", "--restart-backoff", "0.05",
           "--log-dir", str(sup_logs), "--",
           sys.executable, str(CH02), *TRAIN_FLAGS,
           "--max-steps", "6", "--ckpt-freq", "2",
           "-e", "drill", "--save-dir", str(work)]
    os.makedirs(MP_COMPILE_CACHE, exist_ok=True)
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=600, cwd=REPO,
        env=_env(**{faults.ENV_CRASH_STEP: "4"}))
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-3000:]
    assert "attempt 0 failed rc=-9" in proc.stdout     # really SIGKILLed
    assert "attempt 1 exited cleanly" in proc.stdout

    def attempt_text(n):
        d = sup_logs / f"attempt_{n}"
        return ((d / "stdout.log").read_text()
                + (d / "stderr.log").read_text())

    first = losses_by_step(attempt_text(0))
    assert set(first) == {1, 2, 3, 4}                  # died after step 4
    second_text = attempt_text(1)
    assert "Resumed=True" in second_text
    second = losses_by_step(second_text)
    assert set(second) == {5, 6}                       # fast-forwarded
    stitched = {**first, **second}
    for step in golden:
        assert stitched[step] == golden[step], (step, stitched, golden)

    # the supervisor wired a heartbeat file and the loop actually beat it
    hb = json.loads((sup_logs / "attempt_1" / "heartbeat.json").read_text())
    assert hb["step"] >= 5


def test_corrupt_latest_falls_back_and_continues(tmp_path):
    """Run to step 5 with checkpoints at 2 and 4 (keep-n retention), corrupt
    checkpoint-4's shard bytes, then resume: restore must fall back to
    checkpoint-2 via the manifest chain and replay steps 3-5 with the same
    losses the first run logged."""
    exp = ["--ckpt-freq", "2", "-e", "drill", "--save-dir", str(tmp_path)]
    rc, first_text = run_ch02(["--max-steps", "5", *exp])
    assert rc == 0, first_text[-3000:]
    first = losses_by_step(first_text)
    assert set(first) == {1, 2, 3, 4, 5}
    state = json.loads((tmp_path / "drill" / "state.json").read_text())
    assert state["retained"] == ["checkpoint-4", "checkpoint-2"]

    victim = faults.corrupt_checkpoint_dir(tmp_path / "drill" / "checkpoint-4")
    assert victim is not None

    rc, second_text = run_ch02(["--max-steps", "5", *exp])
    assert rc == 0, second_text[-3000:]
    assert "skipping checkpoint checkpoint-4" in second_text
    assert "Resumed=True" in second_text
    second = losses_by_step(second_text)
    assert set(second) == {3, 4, 5}                    # resumed from step 2
    for step in second:
        assert second[step] == first[step], (step, second, first)


def test_corruption_fault_env_var(tmp_path):
    """DTG_FAULT_CORRUPT_CKPT_STEP corrupts the published checkpoint from
    INSIDE the save path (after manifest + state.json) — the operator-facing
    spelling of the drill above."""
    exp = ["--ckpt-freq", "2", "-e", "drill", "--save-dir", str(tmp_path)]
    rc, text = run_ch02(["--max-steps", "4", *exp],
                        env_extra={faults.ENV_CORRUPT_CKPT_STEP: "4"})
    assert rc == 0, text[-3000:]

    from distributed_training_guide_tpu.checkpoint import (load_manifest,
                                                           verify_manifest)

    exp_dir = tmp_path / "drill"
    man = load_manifest(exp_dir, "checkpoint-4")
    assert man is not None
    assert verify_manifest(exp_dir / "checkpoint-4", man)   # really corrupt
    man2 = load_manifest(exp_dir, "checkpoint-2")
    assert verify_manifest(exp_dir / "checkpoint-2", man2) == []


# ---- NaN drills (in-process: the guard work is inside the jitted step) ------

def _nan_args(tmp_path, **over):
    from distributed_training_guide_tpu.train.cli import get_parser

    args = get_parser().parse_args(["-m", "llama-debug"])
    args.dataset_name = "synthetic:60000"
    args.seq_length = 64
    args.batch_size = 1
    args.num_epochs = 1
    args.log_freq = 2
    args.max_steps = 4
    args.save_dir = str(tmp_path)
    for k, v in over.items():
        setattr(args, k, v)
    return args


def test_nan_skip_policy_finishes_run(tmp_path, eight_devices, monkeypatch):
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan
    from distributed_training_guide_tpu.train.cli import run_training

    monkeypatch.setenv(faults.ENV_NAN_LOSS_STEP, "1")
    out = run_training(_nan_args(tmp_path, guard_policy="skip"),
                       lambda: make_plan("ddp", make_mesh()))
    assert out["host_state"]["global_step"] == 4
    assert out["last_info"]["guard_skipped"] == 1      # exactly one skip
    assert np.isfinite(out["last_info"]["running_loss"])


def test_nan_abort_policy_writes_error_file(tmp_path, eight_devices, monkeypatch):
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan
    from distributed_training_guide_tpu.train.cli import run_training
    from distributed_training_guide_tpu.train.guards import NonFiniteLossError

    err = tmp_path / "error.json"
    monkeypatch.setenv("ERROR_FILE", str(err))
    monkeypatch.setenv(faults.ENV_NAN_LOSS_STEP, "1")
    with pytest.raises(NonFiniteLossError, match="step 2"):
        run_training(_nan_args(tmp_path, guard_policy="abort"),
                     lambda: make_plan("ddp", make_mesh()))
    msg = json.loads(err.read_text())["message"]
    assert "NonFiniteLossError" in msg["error"]
    assert "'loss'" in msg["error"]        # offending metrics are recorded
    # the supervisor would classify this as a poison pill: no restart loop
    from distributed_training_guide_tpu.launch.errors import classify_error

    assert classify_error({"message": msg}) == "non-finite"


def test_crash_fault_exception_mode(tmp_path, eight_devices, monkeypatch):
    """DTG_FAULT_CRASH_MODE=exc raises instead of SIGKILL — the drill for
    the @record error-file path (SIGKILL mode can't write one)."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan
    from distributed_training_guide_tpu.train.cli import run_training

    monkeypatch.setenv(faults.ENV_CRASH_STEP, "2")
    monkeypatch.setenv(faults.ENV_CRASH_MODE, "exc")
    with pytest.raises(RuntimeError, match="injected fault: crash at global step 2"):
        run_training(_nan_args(tmp_path), lambda: make_plan("ddp", make_mesh()))
