"""HF rope_scaling parity: every scaled-rope flavor against torch.

The reference inherits rope scaling from HF ``LlamaRotaryEmbedding``
(``01-single-gpu/train_llm.py:57`` trains any HF causal LM; the 405B
chapter's target checkpoint, Llama-3.1, carries ``rope_type: llama3`` —
``05-training-llama-405b/train_llm.py:74-146``). These tests pin full-logits
parity through the real ingestion path (``hf:`` config -> stream-convert ->
sharded load -> forward) for each rope type, plus the unit properties of the
frequency math.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.models.hf_convert import (
    convert_hf_checkpoint, load_pretrained)
from distributed_training_guide_tpu.ops.rope import (
    SEQ_DEPENDENT_ROPE_TYPES, apply_rope, freeze_rope_scaling, rope_type_of,
    scaled_rope_frequencies)
from distributed_training_guide_tpu.parallel import make_mesh, make_plan


def _replicated_shardings(bundle, plan):
    shapes = jax.eval_shape(lambda: bundle.init(bundle.config, jax.random.key(0)))
    return plan.param_shardings(bundle.param_logical_axes(bundle.config), shapes)


def _parity_via_hf_dir(tmp_path, model, seq_len: int, vocab: int = 128):
    """save_pretrained -> hf: ingestion -> convert -> logits vs torch."""
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)
    bundle = get_model(f"hf:{tmp_path / 'hf'}", dtype=jnp.float32)
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")
    ids = np.random.RandomState(0).randint(0, vocab, (2, seq_len))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)
    return bundle


def test_llama3_rope_parity(tmp_path):
    """The VERDICT-r4 headline gap: a ``rope_type: llama3`` checkpoint (the
    Llama-3.1 flavor) must load with correct numerics through ``hf:``."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32},
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    # seq 48 > original_max 32: positions in the rescaled-frequency regime
    bundle = _parity_via_hf_dir(tmp_path, model, seq_len=48)
    assert rope_type_of(bundle.config.rope_scaling) == "llama3"


def test_linear_rope_parity(tmp_path):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0,
        rope_scaling={"rope_type": "linear", "factor": 4.0},
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    _parity_via_hf_dir(tmp_path, model, seq_len=48)


def test_dynamic_ntk_rope_parity(tmp_path):
    """Dynamic NTK engages only past max_position_embeddings; run the test
    sequence BEYOND it so the theta rescale (traced from max(positions)+1,
    like HF's @dynamic_rope_update) is actually exercised."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=32, rope_theta=10000.0,
        rope_scaling={"rope_type": "dynamic", "factor": 4.0},
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    _parity_via_hf_dir(tmp_path, model, seq_len=48)


def test_yarn_rope_parity(tmp_path):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0,
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "original_max_position_embeddings": 64},
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    _parity_via_hf_dir(tmp_path, model, seq_len=48)


def test_longrope_phi3_parity(tmp_path):
    """Phi-3's longrope: per-dim short/long factor lists (top-level
    original_max_position_embeddings folded into the frozen dict at
    ingestion) and the sqrt-log attention temperature on cos/sin."""
    rng = np.random.RandomState(1)
    short = (1.0 + rng.rand(8) * 0.2).round(4).tolist()
    long = (1.0 + rng.rand(8) * 4.0).round(4).tolist()
    hf_cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, original_max_position_embeddings=32,
        rope_theta=10000.0, sliding_window=None,
        rope_scaling={"type": "longrope", "short_factor": short,
                      "long_factor": long},
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.Phi3ForCausalLM(hf_cfg).eval()
    # seq 48 > original 32: the LONG factors + attention temperature path
    bundle = _parity_via_hf_dir(tmp_path, model, seq_len=48)
    s = dict(bundle.config.rope_scaling)
    assert s["original_max_position_embeddings"] == 32
    assert len(s["short_factor"]) == 8


def test_neox_partial_rotary_rope_scaling_parity(tmp_path):
    """rope_scaling composed with NeoX partial rotary: HF computes the
    scaled frequencies at the partial dim (partial_rotary_factor); ours at
    rotary_ndims — pin they agree through real logits."""
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=128, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=256, rotary_pct=0.5, rotary_emb_base=10000,
        rope_scaling={"rope_type": "linear", "factor": 2.0},
        hidden_act="gelu", use_parallel_residual=True,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    _parity_via_hf_dir(tmp_path, model, seq_len=48)


# ---------------------------------------------------------------------------
# unit properties (no torch needed beyond import-skip)
# ---------------------------------------------------------------------------

def test_freeze_roundtrip_and_hashability():
    d = {"rope_type": "longrope", "factor": 2.0,
         "short_factor": [1.0, 1.1], "long_factor": [2.0, 2.2]}
    frozen = freeze_rope_scaling(d)
    hash(frozen)  # usable on frozen dataclass configs
    assert freeze_rope_scaling(frozen) is frozen
    back = dict(frozen)
    assert back["factor"] == 2.0 and back["short_factor"] == (1.0, 1.1)
    assert rope_type_of(frozen) == "longrope"
    assert rope_type_of(None) == "default"
    assert rope_type_of({"type": "linear", "factor": 2.0}) == "linear"  # pre-4.43 key


def test_linear_scaling_halves_frequencies():
    base, f0 = scaled_rope_frequencies(8, 10000.0)
    lin, f1 = scaled_rope_frequencies(8, 10000.0, {"type": "linear", "factor": 2.0})
    np.testing.assert_allclose(np.asarray(lin), np.asarray(base) / 2.0, rtol=1e-6)
    assert f0 == f1 == 1.0


def test_unsupported_rope_type_raises():
    with pytest.raises(ValueError, match="unsupported rope_scaling"):
        scaled_rope_frequencies(8, 10000.0, {"rope_type": "su", "factor": 2.0},
                                max_position=128)


def test_dynamic_below_pivot_is_plain_rope():
    """seq_len <= max_position: the NTK multiplier collapses to 1 (HF
    semantics — scaling engages only past the configured context)."""
    base, _ = scaled_rope_frequencies(8, 10000.0)
    dyn, _ = scaled_rope_frequencies(8, 10000.0,
                                     {"rope_type": "dynamic", "factor": 4.0},
                                     max_position=128, seq_len=64)
    np.testing.assert_allclose(np.asarray(dyn), np.asarray(base), rtol=1e-6)


def test_presets_carry_llama3_scaling():
    from distributed_training_guide_tpu.models.llama import PRESETS

    for name in ("llama-3.1-8b", "llama-3.1-70b", "llama-3.1-405b",
                 "llama-3.2-1b", "llama-3.2-3b"):
        cfg = PRESETS[name]
        assert cfg.max_position_embeddings == 131072, name
        assert rope_type_of(cfg.rope_scaling) == "llama3", name
    # and plain-rope presets still take the fast path
    assert PRESETS["llama-650m"].rope_scaling is None


def test_cp_dynamic_rope_matches_single_device(eight_devices):
    """Dynamic-NTK rope under context parallelism: the frequencies trace
    from ``max(positions) + 1``, and positions are one GLOBAL (cp-sharded)
    array in GSPMD-land outside the attention shard_maps — the reduction
    lowers as a cp-collective max, so every sequence shard derives the SAME
    frequencies. This parity test replaced the old blanket Trainer
    rejection (VERDICT #8a). max_position is set BELOW the trained length
    so the NTK multiplier genuinely engages: a shard-local max (shard 0
    seeing only positions < S/2) would compute different frequencies and
    diverge from the single-device trajectory."""
    from distributed_training_guide_tpu.train import Trainer, adamw_cosine

    assert "dynamic" in SEQ_DEPENDENT_ROPE_TYPES
    scaling = freeze_rope_scaling({"rope_type": "dynamic", "factor": 2.0})
    ids = np.random.RandomState(7).randint(0, 512, (4, 32))

    def run(plan):
        bundle = get_model("llama-debug", rope_scaling=scaling,
                           max_position_embeddings=16, dtype=jnp.float32)
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), plan=plan,
                    donate=False)
        state = t.init_state(0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(2):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses

    golden = run(make_plan("single", make_mesh(devices=jax.devices()[:1])))
    cp = run(make_plan("ddp", make_mesh(cp=2, devices=jax.devices()[:2])))
    np.testing.assert_allclose(cp, golden, rtol=2e-4)


def test_hf_export_roundtrips_rope_scaling(tmp_path):
    """Two-way conversion: export must carry rope_scaling back out (dropping
    it would reload as plain RoPE — silent long-context divergence)."""
    from distributed_training_guide_tpu.models.hf_export import export_hf_checkpoint

    scaling = {"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
               "high_freq_factor": 4.0,
               "original_max_position_embeddings": 64}
    bundle = get_model("llama-debug", dtype=jnp.float32,
                       rope_scaling=freeze_rope_scaling(scaling))
    params = bundle.init(bundle.config, jax.random.key(0))
    out = export_hf_checkpoint(bundle, params, tmp_path / "hf")
    reloaded = transformers.AutoConfig.from_pretrained(out)
    got = dict(reloaded.rope_scaling)
    assert got["rope_type"] == "llama3" and got["factor"] == 8.0

    # longrope: HF reads original_max from the CONFIG TOP LEVEL — an export
    # that keeps it only in-dict crashes HF's rope init on reload (factor
    # stays None). Prove the reloaded config actually initializes.
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    lr = {"rope_type": "longrope", "short_factor": [1.0] * 8,
          "long_factor": [2.0] * 8, "original_max_position_embeddings": 64}
    b2 = get_model("llama-debug", dtype=jnp.float32,
                   rope_scaling=freeze_rope_scaling(lr))
    out2 = export_hf_checkpoint(b2, b2.init(b2.config, jax.random.key(1)),
                                tmp_path / "hf2")
    rl2 = transformers.AutoConfig.from_pretrained(out2)
    assert rl2.original_max_position_embeddings == 64
    inv, factor = ROPE_INIT_FUNCTIONS["longrope"](rl2, device="cpu")
    assert factor >= 1.0 and inv.shape[0] == 8


def test_apply_rope_llama3_matches_hf_freqs():
    """Frequency-level check against transformers' own init function (the
    parity tests above go through full logits; this isolates the math)."""
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    cfg = transformers.LlamaConfig(
        hidden_size=64, num_attention_heads=4, max_position_embeddings=256,
        rope_theta=10000.0,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32})
    want, want_factor = ROPE_INIT_FUNCTIONS["llama3"](cfg, device="cpu")
    got, got_factor = scaled_rope_frequencies(
        16, 10000.0, cfg.rope_scaling, max_position=256)
    np.testing.assert_allclose(np.asarray(got), want.numpy(), rtol=1e-6)
    assert got_factor == want_factor

    ycfg = transformers.LlamaConfig(
        hidden_size=64, num_attention_heads=4, max_position_embeddings=256,
        rope_theta=10000.0,
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "original_max_position_embeddings": 64})
    want, want_factor = ROPE_INIT_FUNCTIONS["yarn"](ycfg, device="cpu")
    got, got_factor = scaled_rope_frequencies(
        16, 10000.0, ycfg.rope_scaling, max_position=256)
    np.testing.assert_allclose(np.asarray(got), want.numpy(), rtol=1e-6)
    np.testing.assert_allclose(got_factor, want_factor, rtol=1e-6)
