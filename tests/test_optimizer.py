"""Optimizer coverage: the AdamW parity path and the Adafactor memory lever.

Reference counterpart: fused AdamW + CosineAnnealingLR
(``01-single-gpu/train_llm.py:73-78``); Adafactor is TPU-native extra
(factored second moment — the memory story the reference solves with CPU
offload instead, ``05-training-llama-405b/train_llm.py:69-72``).
"""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import (Trainer, adafactor_cosine,
                                                  adamw_cosine, lion_cosine)


def _run(optimizer, steps=10, **trainer_kw):
    bundle = get_model("llama-debug")
    t = Trainer(bundle=bundle, optimizer=optimizer, **trainer_kw)
    state = t.init_state(0)
    ids = np.random.RandomState(0).randint(0, bundle.config.vocab_size, (8, 64))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    losses = []
    for _ in range(steps):
        state, m = t.step_fn(state, batch)
        losses.append(float(m["loss"]))
    return losses, state


def _tree_bytes(tree):
    return sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(tree))


def test_adafactor_trains():
    losses, _ = _run(adafactor_cosine(1e-2))
    assert losses[-1] < losses[0] - 0.1, losses


def test_adafactor_state_is_factored():
    """The whole point: optimizer state must be a sliver of AdamW's 2x fp32.
    llama-debug's dims sit under the production factoring threshold (128),
    so lower it to the test scale; real presets (1536+) factor by default."""
    _, fact_state = _run(adafactor_cosine(1e-2, min_dim_size_to_factor=8),
                         steps=1)
    _, adam_state = _run(adamw_cosine(1e-3), steps=1)
    param_bytes = _tree_bytes(fact_state.params)
    assert _tree_bytes(adam_state.opt_state) > 1.9 * param_bytes  # mu + nu
    assert _tree_bytes(fact_state.opt_state) < 0.1 * param_bytes


def test_adafactor_composes_with_fsdp(eight_devices):
    losses, state = _run(adafactor_cosine(1e-2), steps=3,
                         plan=make_plan("fsdp", make_mesh(fsdp=8)), donate=False)
    assert np.isfinite(losses).all()
    # params stay sharded; the (tiny, shape-mismatched) factored state
    # falls back to replicated — assert that stays true and cheap
    wq = state.params["layers"]["attn"]["wq"]
    assert "fsdp" in str(wq.sharding.spec)


def test_adafactor_decay_is_decoupled_and_lr_scaled():
    """optax.adafactor's canned weight_decay_rate applies AFTER lr scaling
    (wd*p per step — ~1e4x AdamW's); our chain must match AdamW's decoupled
    -lr*wd*p instead. Pinned with a zero gradient, where the whole update IS
    the decay term."""
    lr, wd = 3e-5, 0.01
    p = {"w": jnp.ones((256, 256), jnp.float32)}
    tx = adafactor_cosine(lr, weight_decay=wd)
    u, _ = tx.update(jax.tree.map(jnp.zeros_like, p), tx.init(p), p)
    np.testing.assert_allclose(np.asarray(u["w"]), -lr * wd, rtol=1e-3)


def test_lion_trains_with_single_moment():
    """Lion: loss decreases, and optimizer state is exactly ONE moment
    (AdamW keeps two) — the middle rung of the optimizer-memory ladder."""
    losses, state = _run(lion_cosine(1e-3))
    assert losses[-1] < losses[0] - 0.1, losses
    param_bytes = _tree_bytes(state.params)
    moment_bytes = _tree_bytes(state.opt_state)
    assert moment_bytes < 1.1 * param_bytes, (moment_bytes, param_bytes)


def test_lion_decay_is_decoupled_and_lr_scaled():
    """Same pin as adafactor's: with zero gradient the update must be
    -lr*wd*p (optax.lion applies add_decayed_weights before the lr scale)."""
    lr, wd = 3e-5, 0.01
    p = {"w": jnp.ones((256, 256), jnp.float32)}
    tx = lion_cosine(lr, weight_decay=wd)
    u, _ = tx.update(jax.tree.map(jnp.zeros_like, p), tx.init(p), p)
    np.testing.assert_allclose(np.asarray(u["w"]), -lr * wd, rtol=1e-3)


def test_adafactor_checkpoint_roundtrip(tmp_path):
    """Adafactor's FactoredState (row/col accumulators, shapes unlike any
    param) must survive the generic Orbax save/restore path bit-exactly."""
    from distributed_training_guide_tpu.checkpoint import (CheckpointIO,
                                                           abstract_train_state)
    from distributed_training_guide_tpu.train.state import host_state_dict

    bundle = get_model("llama-debug")
    t = Trainer(bundle=bundle, optimizer=adafactor_cosine(1e-2), donate=False)
    state = t.init_state(0)
    ids = np.random.RandomState(0).randint(0, bundle.config.vocab_size, (4, 32))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    state, _ = t.step_fn(state, batch)

    io = CheckpointIO(tmp_path / "exp")
    host = host_state_dict()
    host["global_step"] = 1
    io.save(state, host)
    restored, _ = io.restore(abstract_train_state(t))
    for a, b in zip(jax.tree.leaves(jax.device_get(state.opt_state)),
                    jax.tree.leaves(jax.device_get(restored.opt_state))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # continuing from the restored state is bit-identical to continuing live
    s_live, m_live = t.step_fn(state, batch)
    s_rest, m_rest = t.step_fn(restored, batch)
    assert float(m_live["loss"]) == float(m_rest["loss"])


def test_make_schedule_shapes():
    """Cosine (the reference's CosineAnnealingLR) and linear (DeepSpeed's
    WarmupDecayLR) schedules: endpoints, midpoints, post-t_max flatness,
    warmup ramp, and the loud unknown-decay rejection."""
    import pytest

    from distributed_training_guide_tpu.train.optimizer import make_schedule

    lin = make_schedule(1e-3, t_max=100, eta_min_ratio=0.0, decay="linear")
    np.testing.assert_allclose([float(lin(s)) for s in (0, 50, 100, 150)],
                               [1e-3, 5e-4, 0.0, 0.0], rtol=1e-6, atol=1e-12)

    cos = make_schedule(1e-3, t_max=100, eta_min_ratio=0.01, decay="cosine")
    np.testing.assert_allclose(float(cos(0)), 1e-3, rtol=1e-6)
    np.testing.assert_allclose(float(cos(50)), (1e-3 + 1e-5) / 2, rtol=1e-6)
    np.testing.assert_allclose(float(cos(100)), 1e-5, rtol=1e-6)
    np.testing.assert_allclose(float(cos(200)), 1e-5, rtol=1e-6)

    warm = make_schedule(1e-3, t_max=100, warmup_steps=10, decay="linear")
    assert float(warm(5)) < float(warm(10))

    with pytest.raises(ValueError, match="decay"):
        make_schedule(1e-3, decay="onecycle")
