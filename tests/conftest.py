"""Test harness: force an 8-device virtual CPU platform.

The reference has no test suite (SURVEY.md section 4); its closest analogue is
"torchrun --standalone --nproc-per-node N" smoke runs. The TPU build tests all
mesh/sharding/checkpoint logic hermetically on a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count`` — must be set before jax imports.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "0")

# This image pre-imports jax at interpreter startup (sitecustomize), so the
# env var alone can be too late; the config update below works as long as no
# backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: the suite is dominated by XLA compiles (~5x
# wall-time difference warm-vs-cold), and programs are content-hashed so
# reuse across runs is safe. Override the location with
# JAX_COMPILATION_CACHE_DIR; bench.py shares the same default dir.
_cache = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(__file__)), ".jax_cache"))
try:
    os.makedirs(_cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
except Exception:
    pass  # older jaxlib without the knobs: cold compiles only

import pytest  # noqa: E402

# Marker hygiene is enforced by `--strict-markers` in pyproject.toml: every
# marker must be registered under [tool.pytest.ini_options] markers, and an
# unknown one fails collection loudly instead of silently deselecting wrong.


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) == 8, f"expected 8 virtual devices, got {len(devices)}"
    return devices


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Tier-1 timing report (ROADMAP caveat d: the 870s budget is tight
    even warm): the slowest test calls plus the suite's total test time
    on every run — a creeping compile shows up as a diff in this block,
    not as a surprise timeout three PRs later. (The stock ``--durations``
    flag reports the same numbers but must be remembered per invocation;
    the verify command is pinned in ROADMAP.md, so the report lives in
    conftest where it cannot be forgotten.)"""
    reports = []
    for key in ("passed", "failed", "error"):
        reports.extend(r for r in terminalreporter.stats.get(key, [])
                       if getattr(r, "when", None) == "call")
    if not reports:
        return
    total = sum(r.duration for r in reports)
    slowest = sorted(reports, key=lambda r: r.duration, reverse=True)[:12]
    # the budget assertion: call time must leave real headroom for
    # setup/collection inside ROADMAP's 870s `timeout` — a full tier-1
    # run that eats the margin gets a loud OVER-BUDGET banner in the
    # diffable report (the run itself is not failed here: the enforcing
    # timeout lives in the verify command, this line explains it EARLY)
    budget, margin = 870.0, 120.0
    headroom = budget - margin - total
    full_run = len(reports) > 200        # don't flag `pytest -k one_test`
    flag = (" ** OVER BUDGET — trim or mark slow **"
            if full_run and headroom < 0 else "")
    # fixed host-speed microbench: a 256x256 fp32 numpy matmul x10 —
    # the SAME work every run on every machine, so when the timing
    # block's numbers drift across runs, this line says whether the
    # suite got slower or the host did (a cross-run diff of test
    # durations alone cannot tell the two apart)
    import time as _time

    import numpy as _np

    _a = _np.ones((256, 256), _np.float32)
    _t0 = _time.perf_counter()
    for _ in range(10):
        _a @ _a
    host_ms = (_time.perf_counter() - _t0) * 100.0   # ms per matmul
    terminalreporter.write_sep(
        "-", f"tier-1 timing: {total:.1f}s across {len(reports)} test "
             f"calls (budget {budget:.0f}s incl. setup/collection; "
             f"headroom {headroom:+.1f}s after a {margin:.0f}s "
             f"overhead margin){flag}")
    terminalreporter.write_line(
        f"  host speed: {host_ms:.3f} ms per 256x256 fp32 matmul "
        f"(fixed microbench — normalizes this block across machines)")
    for rep in slowest:
        terminalreporter.write_line(
            f"  {rep.duration:7.2f}s  {rep.nodeid}")
    # newest test families itemized (they are the budget's marginal cost:
    # an older family's creep already shows in the slowest-12 list)
    families = {}
    for rep in reports:
        for fam in ("loadgen", "control"):
            if fam in rep.keywords:
                families.setdefault(fam, [0, 0.0])
                families[fam][0] += 1
                families[fam][1] += rep.duration
    for fam, (n, secs) in sorted(families.items()):
        terminalreporter.write_line(
            f"  family {fam:8s}: {secs:6.2f}s across {n} calls")
