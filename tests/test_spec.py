"""Speculative decoding (serve/spec.py + engine verify path).

The load-bearing property is EXACTNESS: acceptance is coupled to the
target sampler's own deterministic fold_in(seed, position) draws, so
spec-on output must be TOKEN-IDENTICAL to spec-off — greedy and
temperature > 0 alike, for every family, across preemption/replay,
deadline eviction, the tp=2 sharded pool, and the disaggregated pair.
Every test here therefore compares full token streams, never
distributions, and the rollback discipline (lengths retreat, dead k/v
overwritten in place, lookahead pages kept) is pinned by the same pool
invariants the rest of the serve suite enforces.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.serve import (NgramDrafter, Request,
                                                  ServeEngine)
from distributed_training_guide_tpu.serve.api import generate_many
from distributed_training_guide_tpu.serve.spec import DraftModelDrafter
from test_serve import (_cache_page_refs, _check_completions, _drain,
                        _fresh, _pool_invariants, _random_request,
                        _ref_engine, _slot_holders)

pytestmark = [pytest.mark.serve, pytest.mark.spec]


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


# a prompt with internal repetition: the n-gram drafter finds matches and
# the trace actually exercises acceptance, not just the empty-draft path
_REPETITIVE = [9, 8, 7, 9, 8, 7, 9, 8, 7, 9, 8, 7]


def _make_repetitive(req):
    """Swap a random request's prompt for an equal-LENGTH repetitive one
    (lengths drive the trace's budget math — only the content changes)."""
    return dataclasses.replace(req,
                               prompt_ids=_REPETITIVE[:len(req.prompt_ids)])


def _spec_reqs(n, max_new=10):
    return [Request(prompt_ids=_REPETITIVE[:3 + (i % 5)] + [3 + i],
                    max_new_tokens=max_new,
                    temperature=0.0 if i % 2 == 0 else 0.9,
                    top_k=0 if i % 3 else 8, seed=i) for i in range(n)]


# ---- the drafter interface --------------------------------------------------

def test_ngram_drafter_proposals():
    d = NgramDrafter(k=4, max_n=3, min_n=1)
    # trigram suffix [1,2,3] recurs; candidates are what followed it
    ctx = [1, 2, 3, 4, 5, 6, 1, 2, 3]
    assert d.propose(0, ctx, 4) == [4, 5, 6, 1]
    # period-1 cycle: the nearest match truncates at the context end, so
    # the drafter must walk back to an occurrence with a FULL continuation
    assert d.propose(0, [5] * 12, 4) == [5, 5, 5, 5]
    # budget clipping and the no-match case
    assert d.propose(0, ctx, 2) == [4, 5]
    assert d.propose(0, [1, 2, 3, 4], 4) == []
    assert d.propose(0, ctx, 0) == []
    with pytest.raises(ValueError, match="k must be"):
        NgramDrafter(k=0)


def test_lookahead_growth_clamps_never_preempts():
    """ensure_lookahead is opportunistic: with a co-active decode it
    leaves that slot's imminent mandatory-growth page alone (clamping
    the drafts to zero rather than draining the pool into a later
    preemption), and once the neighbor leaves, the same request grows
    freely. Nobody is ever preempted for speculation."""
    from distributed_training_guide_tpu.serve import PagePool, Scheduler

    pool = PagePool(n_pages=4, page_size=4)          # 3 usable
    sched = Scheduler(n_slots=2, pool=pool, max_len=16,
                      max_pages_per_slot=4, prefix_cache=False)
    sched.submit(Request(prompt_ids=[1, 2, 3], max_new_tokens=8))
    sched.submit(Request(prompt_ids=[4, 5, 6], max_new_tokens=1))
    for adm in sched.try_admit():
        sched.commit_tokens(adm.slot_idx, 3)
    # slot 0 wants positions 3..9 (3 pages); 1 page free, but slot 1 is
    # a co-active decode whose mandatory next-write page that free page
    # must remain available for — clamp, don't drain
    assert pool.n_free == 1
    granted = sched.ensure_lookahead(0, 6)
    assert granted == 0
    assert sched.stats["spec_lookahead_clamped"] == 1
    assert sched.stats["preempted"] == 0
    assert all(s is not None for s in sched.slots), "clamp must not evict"
    # slot 1 finishes (max_new=1): its page frees, no co-active decode
    # remains, and the same lookahead now grows for real
    assert sched.record_token(1, 42, from_decode=True) is not None
    granted = sched.ensure_lookahead(0, 6)
    assert granted == 6                  # 3 pages cover positions 0..11
    assert sched.stats["preempted"] == 0
    assert pool.n_free + sum(len(s.pages) for s in sched.slots
                             if s is not None) == pool.capacity


def test_empty_draft_iterations_take_plain_path(llama):
    """A drafter with nothing to propose must not pay the padded
    [S, k+1] verify forward: the iteration falls back to the plain
    single-token program (spec_steps counts verify iterations only),
    and output is unchanged."""
    from distributed_training_guide_tpu.serve import Drafter

    class NullDrafter(Drafter):
        k = 4

        def propose(self, slot_idx, context, budget):
            return []

    bundle, params = llama
    reqs = [Request(prompt_ids=[3, 17, 42], max_new_tokens=8, seed=s)
            for s in range(2)]
    off = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32),
        [_fresh(r) for r in reqs])
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32,
                      speculate=NullDrafter())
    on = generate_many(eng, [_fresh(r) for r in reqs])
    for a, b in zip(off, on):
        assert a.token_ids == b.token_ids
    assert eng.spec["spec_steps"] == 0, "verify ran with nothing drafted"
    assert eng.decode_steps > 0


def test_draft_flash_ineligible_geometry_refused(llama, monkeypatch):
    """attend_impl='flash' with a draft geometry the compiled kernel
    cannot take (the DRAFT model's head_size/page_size, not the
    target's) refuses at construction — not with a Mosaic-gate
    ValueError inside the first draft forward of a live iteration."""
    bundle, params = llama
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    with pytest.raises(ValueError, match="not eligible"):
        DraftModelDrafter(bundle, params, n_slots=2, max_len=32, k=3,
                          page_size=4, attend_impl="flash")
    # 'auto' resolves per-shape (gather for ineligible geometry) and
    # must keep constructing
    DraftModelDrafter(bundle, params, n_slots=2, max_len=32, k=3,
                      page_size=4, attend_impl="auto")


def test_drafter_slot_mismatch_refused(llama):
    """A per-slot-stateful drafter smaller than the engine's decode
    batch refuses at construction, not with an IndexError on the first
    speculative iteration."""
    bundle, params = llama
    drafter = DraftModelDrafter(bundle, params, n_slots=2, max_len=32,
                                k=3, page_size=4)
    with pytest.raises(ValueError, match="slots"):
        ServeEngine(bundle, params, n_slots=4, page_size=4, max_len=32,
                    speculate=drafter)
    with pytest.raises(ValueError, match="speculate must be"):
        ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32,
                    speculate="beam")


# ---- exact acceptance: spec-on == spec-off ---------------------------------

@pytest.mark.parametrize("name", ["llama-debug", "gpt2-debug", "neox-debug",
                                  "moe-debug"])
def test_spec_greedy_and_sampled_identity_across_families(name):
    """The acceptance pin: spec-on output equals the spec-off engine's
    token-for-token — greedy AND temperature > 0 (the coupled acceptance
    emits the target sampler's own draws) — for all four families."""
    over = {"capacity_factor": 4.0} if name == "moe-debug" else {}
    bundle = get_model(name, dtype=jnp.float32, **over)
    params = bundle.init(bundle.config, jax.random.key(0))
    reqs = _spec_reqs(5)
    off = generate_many(
        ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=32),
        [_fresh(r) for r in reqs])
    eng = ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=32,
                      speculate="ngram", spec_k=3)
    on = generate_many(eng, [_fresh(r) for r in reqs])
    for a, b in zip(off, on):
        assert a.token_ids == b.token_ids, f"{name}: spec-on diverged"
    st = eng.stats()
    assert st["spec_tokens_drafted"] > 0, "the trace never speculated"
    assert st["spec_tokens_accepted"] >= 0
    pool = eng.scheduler.pool
    assert pool.n_free + eng.scheduler.cache_pages_held() == pool.capacity


def test_spec_draft_model_identity_and_acceptance(llama):
    """Self-draft (draft model == target): greedy drafts equal the
    target's greedy draws, so acceptance is ~1 and the verify emits
    full k+1 runs; output still equals spec-off exactly. Slot reuse
    across requests exercises the drafter's sync-by-context reseat."""
    bundle, params = llama
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=12, seed=i)
            for i in range(6)]
    off = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32),
        [_fresh(r) for r in reqs])
    drafter = DraftModelDrafter(bundle, params, n_slots=2, max_len=32,
                                k=4, page_size=4)
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32,
                      speculate=drafter)
    on = generate_many(eng, [_fresh(r) for r in reqs])
    for a, b in zip(off, on):
        assert a.token_ids == b.token_ids
    st = eng.stats()
    assert st["spec_acceptance_rate"] > 0.9     # greedy self-draft
    assert st["decode_tokens_per_step"] > 2.0   # real amortization
    assert st["resyncs"] > 0                    # slots were re-seated
    # mixed temperatures still exact (drafts are greedy guesses at a
    # stochastic stream — low acceptance, same tokens)
    mixed = _spec_reqs(4)
    off2 = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32),
        [_fresh(r) for r in mixed])
    on2 = generate_many(eng, [_fresh(r) for r in mixed])
    for a, b in zip(off2, on2):
        assert a.token_ids == b.token_ids


def test_spec_preemption_recompute_identity(llama):
    """Pool pressure under speculation: lookahead growth competes with
    mandatory growth, preemptions fire, and the post-preemption REPLAY
    falls back to the plain decode program (bitwise cache recompute)
    while other slots keep speculating between replays. Every request
    must still match the spec-off batch-1 reference."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=4, page_size=4, max_len=16,
                      n_pages=7, speculate="ngram", spec_k=3)
    reqs = [Request(prompt_ids=_REPETITIVE[:1 + i % 3],
                    max_new_tokens=6 + (i % 5),
                    temperature=0.8 if i % 2 else 0.0, seed=i)
            for i in range(8)]
    res = generate_many(eng, reqs, max_iterations=3000)
    assert eng.scheduler.stats["preempted"] > 0
    ref_eng = _ref_engine(bundle, params, page_size=4, max_len=16)
    for got, req in zip(res, reqs):
        ref = generate_many(ref_eng, [_fresh(req)])[0]
        assert got.token_ids == ref.token_ids, \
            f"seed={req.seed} diverged across preemption under spec"
    pool = eng.scheduler.pool
    assert pool.n_free + eng.scheduler.cache_pages_held() == pool.capacity


@pytest.mark.paged_multitok
def test_spec_flash_family_identity_and_no_downgrade(llama):
    """The block_q=T acceptance pin: (a) 'auto' under speculation is no
    longer downgraded at construction — the engine keeps one attend
    family because the kernel covers decode AND verify, not because it
    retreated to gather; (b) on the FLASH family end-to-end (flash
    decode + flash verify + flash empty-draft fallback), spec-on is
    token-identical to spec-off — greedy and temperature > 0 — the
    identity that used to require the downgrade now holds by
    construction."""
    bundle, params = llama
    eng_auto = ServeEngine(bundle, params, n_slots=2, page_size=4,
                           max_len=32, speculate="ngram", spec_k=3)
    assert eng_auto.attend_impl == "auto", \
        "the construction-time downgrade block is back"
    assert eng_auto.programs.attend_impl == "auto"

    reqs = _spec_reqs(4)                      # greedy + temp>0 mix
    off = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32,
                    attend_impl="flash"),
        [_fresh(r) for r in reqs])
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32,
                      attend_impl="flash", speculate="ngram", spec_k=3)
    on = generate_many(eng, [_fresh(r) for r in reqs])
    for a, b in zip(off, on):
        assert a.token_ids == b.token_ids, "flash-family spec-on diverged"
    assert eng.spec["tokens_drafted"] > 0, "the trace never speculated"


# ---- boundary events mid-speculation (satellite) ---------------------------

def test_deadline_priority_eviction_mid_speculation(llama):
    """A slot evicted by deadline (or displaced by priority) while the
    drafter holds speculative state for it: the eviction is a clean
    iteration-boundary event — the returned tokens are a STRICT PREFIX
    of the batch-1 reference (never a rejected draft), and the pool
    balances after every iteration."""
    bundle, params = llama
    rng = np.random.default_rng(23)
    eng = ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=16,
                      n_pages=8, speculate="ngram", spec_k=3)
    sched, pool = eng.scheduler, eng.scheduler.pool
    done, submitted = [], []
    for it in range(300):
        if rng.random() < 0.35 and len(submitted) < 14:
            req = _make_repetitive(_random_request(rng, len(submitted)))
            submitted.append((eng.submit(req), req))
        done.extend(eng.step())
        _pool_invariants(pool, [_slot_holders(sched, eng.page_size),
                                _cache_page_refs(sched)])
        if len(done) == len(submitted) and not eng.has_work and it > 80:
            break
    done.extend(_drain(eng))
    assert len(done) == len(submitted)
    assert sched.stats["deadline_expired"] > 0
    assert eng.spec["tokens_drafted"] > 0, "the trace never speculated"
    _check_completions(bundle, params, done, submitted, max_len=16)


def test_spec_random_trace_disagg(llama):
    """The disaggregated pair with decode-side speculation under the
    same random trace as test_serve's: speculate/rollback events join
    admit/handoff/evict/preempt, and every pool invariant (refcount ==
    holders incl. in-transit handoffs, capacity identity, no trash page
    live) holds after every iteration."""
    from distributed_training_guide_tpu.serve.disagg import DisaggEngine

    bundle, params = llama
    rng = np.random.default_rng(31)
    eng = DisaggEngine(bundle, params, n_slots=3, n_prefill_slots=2,
                       page_size=4, max_len=16, n_pages=9,
                       prefill_chunk=4, speculate="ngram", spec_k=3)
    done, submitted = [], []
    for it in range(400):
        if rng.random() < 0.3 and len(submitted) < 16:
            req = _make_repetitive(_random_request(rng, len(submitted)))
            submitted.append((eng.submit(req), req))
        done.extend(eng.step())
        transit: dict = {}
        for h in eng.handoff.pending:
            assert 0 not in h.pages
            for p in h.pages:
                transit[p] = transit.get(p, 0) + 1
        _pool_invariants(eng.pool, [
            _slot_holders(eng.prefill.sched, eng.page_size),
            _slot_holders(eng.decode.sched, eng.page_size),
            transit, _cache_page_refs(eng.prefill.sched)])
        if len(done) == len(submitted) and not eng.has_work and it > 100:
            break
    done.extend(_drain(eng))
    assert len(done) == len(submitted)
    assert eng.decode.spec["tokens_drafted"] > 0
    assert eng.stats()["handoff_bytes_copied"] == 0
    _check_completions(bundle, params, done, submitted, max_len=16)


def test_spec_sharded_tp2_trace(llama, eight_devices):
    """Speculation over the tp=2 SHARDED pool: the verify program's
    multi-token attend runs per chip inside the manual region exactly as
    the chunk program does. Short random trace — invariants every
    iteration, completions vs batch-1."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    bundle, params = llama
    plan = make_plan("tp", make_mesh(tp=2, devices=eight_devices[:2]))
    rng = np.random.default_rng(17)
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                      n_pages=8, plan=plan, shard_kv=True,
                      speculate="ngram", spec_k=3)
    sched, pool = eng.scheduler, eng.scheduler.pool
    done, submitted = [], []
    for it in range(200):
        if rng.random() < 0.35 and len(submitted) < 8:
            req = dataclasses.replace(
                _make_repetitive(_random_request(rng, len(submitted))),
                deadline_s=None)
            submitted.append((eng.submit(req), req))
        done.extend(eng.step())
        _pool_invariants(pool, [_slot_holders(sched, eng.page_size),
                                _cache_page_refs(sched)])
        if len(done) == len(submitted) and not eng.has_work and it > 60:
            break
    done.extend(_drain(eng))
    assert len(done) == len(submitted)
    assert eng.spec["tokens_drafted"] > 0
    _check_completions(bundle, params, done, submitted, max_len=16)


@pytest.mark.slow
def test_spec_sharded_tp2_grid(llama, eight_devices):
    """The >=2-device spec grid (slow): tp=2 sharded pool x {ngram,
    self-draft} x mixed temperatures, full identity vs the unsharded
    spec-off engine."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    bundle, params = llama
    plan = make_plan("tp", make_mesh(tp=2, devices=eight_devices[:2]))
    reqs = _spec_reqs(6, max_new=12)
    off = generate_many(
        ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=32),
        [_fresh(r) for r in reqs])
    for speculate in ("ngram",
                      DraftModelDrafter(bundle, params, n_slots=3,
                                        max_len=32, k=3, page_size=4)):
        eng = ServeEngine(bundle, params, n_slots=3, page_size=4,
                          max_len=32, plan=plan, shard_kv=True,
                          speculate=speculate, spec_k=3)
        on = generate_many(eng, [_fresh(r) for r in reqs])
        for a, b in zip(off, on):
            assert a.token_ids == b.token_ids
        assert eng.spec["spec_steps"] > 0


# ---- stats / streaming plumbing (satellites) -------------------------------

def test_spec_and_cache_stats_surface(llama):
    """stats() (and therefore /healthz, which serves it verbatim) must
    expose the speculation counters AND the prefix-cache pressure pair —
    eviction count + cached-page BYTES (satellite: a thrashing cache
    previously looked healthy because only the hit rate was visible)."""
    from distributed_training_guide_tpu.serve import kv_page_bytes
    from distributed_training_guide_tpu.serve.api import (_EngineWorker,
                                                          throughput_stats)
    import time as _t

    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                      speculate="ngram", spec_k=3)
    t0 = _t.perf_counter()
    res = generate_many(eng, [Request(prompt_ids=_REPETITIVE[:8],
                                      max_new_tokens=6, seed=s)
                              for s in range(3)])
    st = eng.stats()
    for key in ("spec_steps", "spec_tokens_drafted", "spec_tokens_accepted",
                "spec_tokens_rejected", "spec_acceptance_rate",
                "decode_tokens_per_step", "cache_evicted_pages",
                "pages_cached_bytes", "spec_lookahead_clamped"):
        assert key in st, f"stats() lost {key}"
    assert st["pages_cached_bytes"] == st["pages_cached"] * kv_page_bytes(
        bundle.config, page_size=4)
    assert st["pages_cached"] > 0 and st["pages_cached_bytes"] > 0
    # the worker snapshot (what /healthz serves) carries the same keys
    worker = _EngineWorker(eng)
    assert "spec_acceptance_rate" in worker.stats()
    assert "pages_cached_bytes" in worker.stats()
    # and the batch-level aggregate forwards the speculation block
    agg = throughput_stats(res, _t.perf_counter() - t0, eng)
    assert agg["spec_tokens_drafted"] == st["spec_tokens_drafted"]
    assert agg["decode_tokens_per_step"] == st["decode_tokens_per_step"]


def test_spec_accepted_run_flushes_per_iteration(llama):
    """Streaming under speculation: an iteration that accepts a run of
    drafts appends the WHOLE run to partial_tokens() at that boundary
    (grow-only lists — the dedup-by-count consumer sees a multi-token
    delta, never a rewrite)."""
    bundle, params = llama
    drafter = DraftModelDrafter(bundle, params, n_slots=1, max_len=32,
                                k=4, page_size=4)
    eng = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=32,
                      speculate=drafter)
    rid = eng.submit(Request(prompt_ids=[3, 17, 42], max_new_tokens=12))
    prev, deltas = [], []
    while eng.has_work:
        eng.step()
        toks = eng.partial_tokens().get(rid, prev)
        assert toks[:len(prev)] == prev, "stream rewrote history"
        deltas.append(len(toks) - len(prev))
        prev = toks
    assert max(deltas) > 1, "no multi-token flush despite acceptance"
