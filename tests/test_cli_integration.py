"""End-to-end chapter-loop integration tests through run_training.

The reference's only 'tests' are its runnable smoke commands (SURVEY.md §4);
these are those smoke runs as pytest: full loop (data -> sharded step ->
logging -> checkpoint -> resume) on the virtual 8-device mesh, for the ddp and
tp_fsdp plans, plus the engine facade.
"""
import argparse

import jax
import numpy as np
import pytest

from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train.cli import get_parser, run_training


def make_args(tmp_path, **over):
    args = get_parser().parse_args(["-m", "llama-debug"])
    args.dataset_name = "synthetic:60000"
    args.seq_length = 64
    args.batch_size = 1
    args.num_epochs = 1
    args.log_freq = 2
    args.max_steps = 4
    args.save_dir = str(tmp_path)
    for k, v in over.items():
        setattr(args, k, v)
    return args


def test_run_training_ddp(tmp_path, eight_devices):
    args = make_args(tmp_path)
    out = run_training(args, lambda: make_plan("ddp", make_mesh()))
    assert out["host_state"]["global_step"] == 4
    assert np.isfinite(out["last_info"]["running_loss"])
    assert out["last_info"]["tokens_per_s"] > 0


def test_run_training_sliding_window_flag(tmp_path, eight_devices):
    """--sliding-window W overrides the model config and trains through the
    banded attention; loss differs from the full-causal run (the band binds)."""
    full = run_training(make_args(tmp_path / "a"),
                        lambda: make_plan("ddp", make_mesh()))
    swa = run_training(make_args(tmp_path / "b", sliding_window=16),
                       lambda: make_plan("ddp", make_mesh()))
    assert np.isfinite(swa["last_info"]["running_loss"])
    assert (abs(swa["last_info"]["running_loss"]
                - full["last_info"]["running_loss"]) > 1e-6)


def test_run_training_profile_trace(tmp_path, eight_devices):
    """--profile-dir captures a steady-state jax.profiler window (steps
    10-15, the C22 diagnostics surface) — never exercised by the other
    smokes, whose max_steps stops before the trace starts."""
    args = make_args(tmp_path, profile_dir=str(tmp_path / "prof"),
                     max_steps=15)
    run_training(args, lambda: make_plan("ddp", make_mesh()))
    produced = [p for p in (tmp_path / "prof").rglob("*") if p.is_file()]
    assert produced, "profiler trace directory is empty"


def test_run_training_fence_every_matches_per_step(tmp_path, eight_devices):
    """--fence-every N banks device losses and drains at fence/log/ckpt
    boundaries (the bench-measured 695->618 ms dispatch-ahead lever,
    BENCH.md). The computation is unchanged, so the logged running_loss
    trajectory must be BIT-identical to the per-step-fenced default —
    including a fence group (3) that doesn't divide log_freq (2)."""
    out1 = run_training(make_args(tmp_path / "f1"),
                        lambda: make_plan("ddp", make_mesh()))
    out3 = run_training(make_args(tmp_path / "f3", fence_every=3),
                        lambda: make_plan("ddp", make_mesh()))
    assert out3["last_info"]["running_loss"] == out1["last_info"]["running_loss"]
    assert out3["host_state"]["global_step"] == out1["host_state"]["global_step"]


def test_run_training_param_dtype_bf16(tmp_path, eight_devices):
    """--param-dtype bfloat16 (the bench sweep's bf16-state lever as a
    product flag): params AND the mirrored optimizer moments store in bf16."""
    import jax.numpy as jnp

    args = make_args(tmp_path, param_dtype="bfloat16")
    out = run_training(args, lambda: make_plan("ddp", make_mesh()))
    assert out["host_state"]["global_step"] == 4
    assert all(l.dtype == jnp.bfloat16
               for l in jax.tree.leaves(out["state"].params))


def test_run_training_fence_every_rejects_zero(tmp_path, eight_devices):
    with pytest.raises(SystemExit):
        run_training(make_args(tmp_path, fence_every=0),
                     lambda: make_plan("ddp", make_mesh()))


def test_run_training_timer_sync(tmp_path, eight_devices):
    """--timer-sync (VERDICT r3 item 9): the device-fenced per-phase timer
    mode — C17's reference semantics — runs the loop and produces nonzero
    phase walltimes."""
    args = make_args(tmp_path, timer_sync=True)
    out = run_training(args, lambda: make_plan("ddp", make_mesh()))
    assert out["host_state"]["global_step"] == 4
    assert out["last_info"]["time/step"] > 0


def test_device_sync_fences_dispatched_work(eight_devices):
    """device_sync must actually wait for in-flight device work: timing an
    async dispatch with the fence measures the compute, without it only the
    dispatch."""
    import jax.numpy as jnp

    from distributed_training_guide_tpu.utils.timers import LocalTimer, device_sync

    f = jax.jit(lambda x: (x @ x) @ x)
    x = jnp.ones((1500, 1500))
    jax.block_until_ready(f(x))     # compile outside the timed region
    unsynced, synced = LocalTimer(), LocalTimer(sync_fn=device_sync)
    for _ in range(3):
        with unsynced:
            f(x)                    # async dispatch returns immediately
        jax.block_until_ready(f(x))  # drain so the next dispatch is clean
        with synced:
            f(x)                    # fence on __exit__ waits for the matmuls
    assert synced.avg_elapsed_ms() > unsynced.avg_elapsed_ms()


def test_run_training_tp_fsdp_with_accum(tmp_path, eight_devices):
    args = make_args(tmp_path, grad_accum=2, batch_size=2,
                     checkpoint_activations=True)
    out = run_training(args, lambda: make_plan("tp_fsdp", make_mesh(tp=2, fsdp=2)))
    assert out["host_state"]["global_step"] == 4


def test_run_training_checkpoint_resume(tmp_path, eight_devices):
    args = make_args(tmp_path, experiment_name="exp", ckpt_freq=2, max_steps=3)
    plan_factory = lambda: make_plan("fsdp", make_mesh(fsdp=8))
    out1 = run_training(args, plan_factory)
    assert out1["host_state"]["global_step"] == 3
    # second invocation resumes from step 2's checkpoint and continues
    args2 = make_args(tmp_path, experiment_name="exp", ckpt_freq=2, max_steps=5)
    out2 = run_training(args2, plan_factory)
    assert out2["host_state"]["global_step"] == 5
    assert int(out2["state"].step) >= 3


def test_run_training_fence_checkpoint_resume_exact(tmp_path, eight_devices):
    """Resume under --fence-every where the fence group (3) straddles the
    checkpoint boundary (ckpt_freq 2): the pre-save drain must leave
    host_state's running_loss current, so the resumed run's logged
    trajectory is bit-identical to an uninterrupted per-step-fenced run."""
    plan_factory = lambda: make_plan("ddp", make_mesh())
    golden = run_training(make_args(tmp_path / "g", log_freq=5, max_steps=5),
                          plan_factory)

    args = make_args(tmp_path / "r", experiment_name="exp", ckpt_freq=2,
                     log_freq=5, max_steps=3, fence_every=3)
    out1 = run_training(args, plan_factory)
    assert out1["host_state"]["global_step"] == 3
    # resume must actually engage — otherwise run 2 retrains 1-5 from
    # scratch and the bit-equality below would pass vacuously
    from distributed_training_guide_tpu.checkpoint import CheckpointIO

    assert CheckpointIO(tmp_path / "r" / "exp").can_resume()
    args2 = make_args(tmp_path / "r", experiment_name="exp", ckpt_freq=2,
                      log_freq=5, max_steps=5, fence_every=3)
    out2 = run_training(args2, plan_factory)
    assert out2["host_state"]["global_step"] == 5
    assert int(out2["state"].step) >= 3  # continued, not retrained
    assert (out2["last_info"]["running_loss"]
            == golden["last_info"]["running_loss"])


def test_engine_roundtrip(tmp_path, eight_devices):
    from distributed_training_guide_tpu.train.engine import initialize

    config = {
        "model": "llama-debug",
        "zero_optimization": {"stage": 1},
        "tensor_parallel": 2,
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
    }
    engine = initialize(config)
    # stage1 + tp must keep ZeRO-1 opt-state sharding
    mu = engine.state.opt_state[0].mu["layers"]["attn"]["wq"]
    assert any(s is not None for s in mu.sharding.spec)
    ids = np.random.RandomState(0).randint(0, 512, (engine.global_batch_size, 32))
    batch_sh = engine.trainer.batch_shardings()
    batch = {k: jax.device_put(ids, batch_sh[k]) for k in ("input_ids", "labels")}
    m1 = engine.train_batch(batch)
    assert np.isfinite(m1["loss"])
    engine.save_checkpoint(tmp_path / "eng")
    host = engine.load_checkpoint(tmp_path / "eng")
    assert host["global_step"] == 1


def test_engine_accepts_canonical_deepspeed_config(eight_devices):
    """A config in the REFERENCE's exact ds_config.json shape (nested
    WarmupCosineLR scheduler params, offload flags under zero_optimization)
    must be honored, not silently ignored — only `model` is added."""
    from distributed_training_guide_tpu.train.engine import initialize

    config = {
        "model": "llama-debug",
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-5}},
        "scheduler": {"type": "WarmupCosineLR",
                      "params": {"total_num_steps": 777,
                                 "warmup_num_steps": 5,
                                 "cos_min_ratio": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3, "offload_param": False,
                              "offload_optimizer": False},
    }
    engine = initialize(config)
    assert engine.scheduler_config == {"t_max": 772, "warmup_steps": 5,
                                       "eta_min_ratio": 1e-2,
                                       "decay": "cosine"}  # 777 - 5 warmup:
    # DS decay ENDS at total_num_steps; native t_max counts post-warmup
    assert not engine.trainer.offload_opt_state
    ids = np.random.RandomState(0).randint(0, 512, (engine.global_batch_size, 32))
    batch_sh = engine.trainer.batch_shardings()
    batch = {k: jax.device_put(ids, batch_sh[k]) for k in ("input_ids", "labels")}
    assert np.isfinite(engine.train_batch(batch)["loss"])

    # the {"device": "none"} dict is DeepSpeed's canonical DISABLE spelling
    # — a truthy-dict check would invert it
    off = initialize({"model": "llama-debug",
                      "zero_optimization": {
                          "stage": 3,
                          "offload_optimizer": {"device": "none"},
                          "offload_param": {"device": "none"}}})
    assert not off.trainer.offload_opt_state and not off.trainer.offload_params

    with pytest.raises(ValueError, match="scheduler.type"):
        initialize({"model": "llama-debug",
                    "scheduler": {"type": "OneCycle", "params": {}}})
    with pytest.raises(ValueError, match="scheduler.params"):
        initialize({"model": "llama-debug",
                    "scheduler": {"type": "WarmupCosineLR",
                                  "params": {"warmup_max_lr": 1e-4}}})

    # WarmupDecayLR = DS's linear decay-to-zero; it maps to the linear
    # schedule (NOT silently onto cosine), and cos_min_ratio is invalid there
    lin = initialize({"model": "llama-debug",
                      "scheduler": {"type": "WarmupDecayLR",
                                    "params": {"total_num_steps": 500,
                                               "warmup_num_steps": 10}}})
    assert lin.scheduler_config == {"t_max": 490, "warmup_steps": 10,
                                    "eta_min_ratio": 0.0, "decay": "linear"}
    with pytest.raises(ValueError, match="scheduler.params"):
        initialize({"model": "llama-debug",
                    "scheduler": {"type": "WarmupDecayLR",
                                  "params": {"cos_min_ratio": 0.1}}})


def test_engine_optimizer_type_dispatch(eight_devices):
    from distributed_training_guide_tpu.train.engine import initialize

    config = {
        "model": "llama-debug",
        "zero_optimization": {"stage": 3},
        "optimizer": {"type": "Adafactor", "params": {"lr": 1e-2}},
    }
    engine = initialize(config)
    ids = np.random.RandomState(0).randint(0, 512, (engine.global_batch_size, 32))
    batch_sh = engine.trainer.batch_shardings()
    batch = {k: jax.device_put(ids, batch_sh[k]) for k in ("input_ids", "labels")}
    assert np.isfinite(engine.train_batch(batch)["loss"])
    # the config actually selected adafactor: no fp32 Adam mu anywhere
    state_names = {type(s).__name__ for s in engine.state.opt_state}
    assert "ScaleByAdamState" not in state_names

    lion_engine = initialize({"model": "llama-debug",
                              "optimizer": {"type": "Lion",
                                            "params": {"lr": 1e-4}}})
    lion_names = {type(s).__name__ for s in lion_engine.state.opt_state}
    assert "ScaleByLionState" in lion_names
    with pytest.raises(ValueError, match="optimizer.type"):
        initialize({"model": "llama-debug", "optimizer": {"type": "SGD"}})
    # 'eps' is in virtually every DeepSpeed-ported AdamW config (ADVICE r3):
    # it must load — and actually reach optax — not hard-error as unknown
    eps_engine = initialize({"model": "llama-debug",
                             "optimizer": {"type": "AdamW",
                                           "params": {"lr": 1e-3,
                                                      "eps": 1e-6}}})
    assert eps_engine is not None
    # ...but eps stays rejected for optimizers that have no such knob
    with pytest.raises(ValueError, match="eps"):
        initialize({"model": "llama-debug",
                    "optimizer": {"type": "Lion",
                                  "params": {"lr": 1e-4, "eps": 1e-6}}})


def test_engine_full_strategy_space(tmp_path, eight_devices):
    """The engine config covers pp/cp/ep + context_impl + remat policy, not
    just ZeRO stage + tp: a pp x tp config must build the pipeline plan and
    train, and the strategy-derivation guards must fire on bad combos."""
    from distributed_training_guide_tpu.train.engine import initialize

    engine = initialize({
        "model": "llama-debug",
        "zero_optimization": {"stage": 0},
        "tensor_parallel": 2,
        "pipeline_parallel": 2,
        "pp_microbatches": 2,
        "activation_checkpointing": {"enabled": True, "policy": "attn"},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
    })
    assert engine.trainer.plan.strategy == "pp_tp"
    assert dict(engine.trainer.plan.mesh.shape)["pp"] == 2
    assert engine.trainer.remat and engine.trainer.remat_policy == "attn"
    ids = np.random.RandomState(0).randint(0, 512, (4, 32))
    batch_sh = engine.trainer.batch_shardings()
    batch = {k: jax.device_put(ids, batch_sh[k])
             for k in ("input_ids", "labels")}
    losses = [engine.train_batch(batch)["loss"] for _ in range(2)]
    assert np.isfinite(losses).all() and losses[1] < losses[0]
    # the DeepSpeed-surface checkpoint API works on the pp x tp plan too
    # (pp-sharded layer stacks through abstract_train_state) — values, not
    # just host metadata, must round-trip
    leaf_before = np.asarray(
        jax.device_get(jax.tree.leaves(engine.state.params)[0]))
    engine.save_checkpoint(tmp_path / "eng_pp")
    engine.state = engine.trainer.init_state(1)  # clobber, then restore
    assert engine.load_checkpoint(tmp_path / "eng_pp")["global_step"] == 2
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(jax.tree.leaves(engine.state.params)[0])),
        leaf_before)

    # cp rides any strategy as a mesh axis + context_impl
    cp_engine = initialize({"model": "llama-debug", "context_parallel": 2,
                            "context_impl": "ulysses"})
    assert dict(cp_engine.trainer.plan.mesh.shape)["cp"] == 2
    assert cp_engine.trainer.context_impl == "ulysses"

    # ep x tp has no plan; ZeRO-1 x pp has no sharding rules — both must
    # fail loudly instead of silently dropping an axis
    with pytest.raises(ValueError, match="expert_parallel"):
        initialize({"model": "moe-debug", "expert_parallel": 2,
                    "tensor_parallel": 2})
    with pytest.raises(ValueError, match="stage"):
        initialize({"model": "llama-debug",
                    "zero_optimization": {"stage": 1},
                    "pipeline_parallel": 2})


def test_engine_moe_dispatch_key(eight_devices):
    """Top-level moe_dispatch threads to the model config and trains (the
    dp-sharded ragged path runs in the manual shard_map); non-MoE models
    reject the key loudly."""
    import jax.numpy as jnp

    from distributed_training_guide_tpu.train.engine import initialize

    engine = initialize({"model": "moe-debug", "moe_dispatch": "ragged",
                         "bf16": {"enabled": False}})
    assert engine.trainer.bundle.config.moe_dispatch == "ragged"
    ids = np.random.RandomState(0).randint(0, 512, (8, 16))
    batch_sh = engine.trainer.batch_shardings()
    batch = {k: jax.device_put(ids, batch_sh[k])
             for k in ("input_ids", "labels")}
    m = engine.train_batch(batch)
    assert np.isfinite(float(m["loss"]))
    assert float(m["moe_dropped_frac"]) == 0.0
    with pytest.raises(ValueError, match="moe_dispatch"):
        initialize({"model": "llama-debug", "moe_dispatch": "ragged"})


def test_preflight_budget_and_lowering(eight_devices):
    import jax.numpy as jnp

    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan
    from distributed_training_guide_tpu.train import Trainer, adamw_cosine
    from distributed_training_guide_tpu.train.preflight import run_preflight

    bundle = get_model("llama-debug")
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("fsdp", make_mesh(fsdp=8)), donate=False)
    rep = run_preflight(t, global_batch=8, seq_length=64)
    assert rep["lowered"] and rep["n_devices"] == 8
    assert "moe_dispatch" not in rep   # dense families aren't priced

    # serving-side KV pricing rides every preflight (serve/kv_pages.py):
    # pages x layers x 2 (k,v) x page_size x kv_heads x head_dim bytes
    sk = rep["serve_kv"]
    dcfg = bundle.config
    assert sk["pages_per_slot_at_seq"] == 4          # ceil(64 / 16)
    assert sk["bytes_per_page"] == (
        dcfg.num_layers * 2 * 16 * dcfg.num_kv_heads * dcfg.head_size
        * jnp.dtype(dcfg.dtype).itemsize)
    assert sk["bytes_per_slot_at_seq"] == 4 * sk["bytes_per_page"]
    # the dense column pays the full position table per slot
    assert sk["dense_bytes_per_slot"] == (
        sk["bytes_per_page"] // 16 * dcfg.max_position_embeddings)
    # decode traffic: the flash kernel reads the live context once per
    # token; the gather view moved ~3x that (read pool + write view +
    # read view). Prefix sharing amortizes the nominal system prompt's
    # full pages per extra co-resident slot (clamped to the context).
    assert sk["decode_read_bytes_per_token_flash"] == \
        sk["bytes_per_slot_at_seq"]
    assert sk["decode_traffic_bytes_per_token_gather"] == \
        3 * sk["bytes_per_slot_at_seq"]
    assert sk["shared_prefix_tokens_nominal"] == 64          # min(512, seq)
    assert sk["shared_prefix_bytes_amortized_per_extra_slot"] == \
        4 * sk["bytes_per_page"]
    # multi-token forwards (the block_q=T kernel family): a verify step
    # and a prefill chunk each read the context ONCE through the kernel
    # (same O(context) bytes as a decode token, amortized over T rows);
    # the gather form paid the 3x round-trip per forward. The per-token
    # verify row divides the kernel read over k+1 at full acceptance.
    assert sk["verify_read_bytes_per_step_flash"] == \
        sk["bytes_per_slot_at_seq"]
    assert sk["verify_traffic_bytes_per_step_gather"] == \
        3 * sk["bytes_per_slot_at_seq"]
    assert sk["chunk_prefill_read_bytes_per_chunk_flash"] == \
        sk["bytes_per_slot_at_seq"]
    assert sk["chunk_prefill_traffic_bytes_per_chunk_gather"] == \
        3 * sk["bytes_per_slot_at_seq"]
    assert sk["verify_read_bytes_per_token_flash_accept_1.0"] == \
        sk["bytes_per_slot_at_seq"] // (sk["spec_k_nominal"] + 1)
    # fsdp mesh: tp=1, pool replicated — per-chip column equals the full
    # one; handoff is 0 B same-host, per-slot payload cross-host
    assert sk["kv_shards"] == 1
    assert sk["bytes_per_page_per_chip"] == sk["bytes_per_page"]
    assert sk["handoff_bytes_same_host"] == 0
    assert sk["handoff_bytes_cross_host_at_seq"] == \
        sk["bytes_per_slot_at_seq"]
    # kv_dtype rows (quantized KV pages, serve/kv_pages.py): the int8
    # figure INCLUDES the per-(position, kv-head) fp32 scales — payload
    # bytes alone would overstate the capacity win
    by = sk["bytes_per_page_by_kv_dtype"]
    model_dtype = ("bf16" if jnp.dtype(dcfg.dtype) == jnp.bfloat16
                   else "fp32")
    assert by[model_dtype] == sk["bytes_per_page"]   # headline row = model
    assert by["fp32"] == (dcfg.num_layers * 2 * 16 * dcfg.num_kv_heads
                          * dcfg.head_size * 4)
    assert by["int8"] == (dcfg.num_layers * 2 * 16 * dcfg.num_kv_heads
                          * (dcfg.head_size + 4))
    assert sk["bytes_per_slot_by_kv_dtype"]["int8"] == 4 * by["int8"]
    assert sk["int8_bytes_vs_fp32"] <= 0.55
    # tiered-KV rows (serve/tiering.py): one spilled slot parks exactly
    # the per-slot pool bytes host-side (by dtype — the int8 row ships
    # its scales), a directory pull moves those same bytes once over the
    # wire, and the FLOPs-per-pull-byte ratio prices the pull against
    # re-prefilling at the training context
    assert sk["host_tier_bytes_per_spilled_slot_at_seq"] == \
        sk["bytes_per_slot_at_seq"]
    assert sk["host_tier_bytes_per_spilled_slot_by_kv_dtype"] == \
        sk["bytes_per_slot_by_kv_dtype"]
    assert sk["host_tier_slots_per_gib"] == \
        (1 << 30) // sk["bytes_per_slot_at_seq"]
    assert sk["directory_pull_wire_bytes_at_seq"] == \
        sk["bytes_per_slot_at_seq"]
    assert sk["reprefill_flops_at_seq"] == \
        2 * bundle.num_active_params() * 64
    assert sk["reprefill_flops_per_pull_byte"] == round(
        sk["reprefill_flops_at_seq"] / sk["bytes_per_slot_at_seq"], 2)

    # weight_dtype rows (serve/weights.py): STORAGE bytes per dtype —
    # the int8 row includes the per-block fp32 scales, same rule as the
    # kv rows above — and a publish or generation swap moves exactly
    # these bytes, so the payload tables equal the storage table
    sw = rep["serve_weights"]
    wb = sw["weight_bytes_by_dtype"]
    n_weights = sum(
        int(np.prod(sd.shape, dtype=np.int64)) for sd in jax.tree.leaves(
            jax.eval_shape(lambda: bundle.init(dcfg, jax.random.key(0)))))
    assert wb["fp32"] == 4 * n_weights
    assert wb["bf16"] == 2 * n_weights
    assert sw["int8_supported"] and 0 < wb["int8"] < wb["bf16"]
    assert sw["publish_payload_bytes_by_dtype"] == wb
    assert sw["swap_payload_bytes_by_dtype"] == wb
    # the acceptance pin: int8 weights (scales included) at least 1.9x
    # smaller than fp32 on every publish/swap payload
    assert sw["int8_bytes_vs_fp32"] <= 0.53
    # ...and the analytic rows match what an engine actually holds
    from distributed_training_guide_tpu.serve.engine import ServeEngine
    w_eng = ServeEngine(bundle, bundle.init(dcfg, jax.random.key(0)),
                        n_slots=2, page_size=16, max_len=64,
                        weight_dtype="int8")
    assert w_eng.weight_bytes() == wb["int8"]

    # adapter-pool rows (serve/adapters.py): the multi-LoRA pool priced
    # at the nominal serving shape (8 slots, rank 8, wq+wv) — fp32
    # factors A [L, e, r] + B [L, r, fan_out] per target, so the bytes
    # pin arithmetically from the config; the publish payload is ONE
    # adapter's factors (the consolidation lever vs a full publish)
    sa = rep["serve_adapters"]
    hq = dcfg.num_heads * dcfg.head_size
    hkv = dcfg.num_kv_heads * dcfg.head_size
    e, l, r = dcfg.hidden_size, dcfg.num_layers, 8
    per = 4 * l * ((e * r + r * hq) + (e * r + r * hkv))
    assert sa["max_adapters"] == 8 and sa["rank"] == 8
    assert sa["targets"] == ["wq", "wv"]
    assert sa["bytes_per_adapter"] == per
    assert sa["pool_bytes"] == 8 * per
    assert sa["publish_payload_bytes"] == per
    assert sa["pool_vs_fp32_weights"] == round(8 * per / wb["fp32"], 4)
    # ...and the analytic rows match what a pooled engine reports
    a_eng = ServeEngine(bundle, bundle.init(dcfg, jax.random.key(0)),
                        n_slots=2, page_size=16, max_len=64,
                        max_adapters=8, adapter_rank=8)
    a_rep = a_eng.adapter_report()
    assert a_rep["bytes_per_adapter"] == per
    assert a_rep["pool_bytes"] == 8 * per

    # colocation pricing under QLoRA (post/loop.py): the engine's merged
    # copy is priced at ITS weight_dtype — quantized base + fp adapters
    # in the trainer + an fp teacher all priced in one report
    from distributed_training_guide_tpu.models.lora import lora_bundle
    from distributed_training_guide_tpu.train.preflight import \
        price_post_colocation
    lt = Trainer(bundle=lora_bundle(bundle, rank=4),
                 optimizer=adamw_cosine(1e-3), lora_only=True)
    colo = price_post_colocation(lt, n_slots=4, max_len=64,
                                 weight_dtype="int8", teacher_bundle=bundle)
    assert colo["engine_weight_dtype"] == "int8"
    assert colo["engine_param_bytes"] == wb["int8"]
    assert colo["teacher_param_bytes"] == wb["fp32"]
    colo_fp = price_post_colocation(lt, n_slots=4, max_len=64)
    assert colo_fp["engine_weight_dtype"] == "model"
    assert colo_fp["engine_param_bytes"] == wb["fp32"]
    assert colo["total_bytes"] == \
        colo_fp["total_bytes"] - wb["fp32"] + wb["int8"] + wb["fp32"]

    # tp mesh: the sharded pool (serve/sharding.py kv-head split) halves
    # the per-CHIP page/slot bytes at tp=2 (llama-debug: 2 kv heads)
    tp_t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                   plan=make_plan("tp", make_mesh(
                       tp=2, devices=eight_devices[:2])), donate=False)
    tp_sk = run_preflight(tp_t, global_batch=2, seq_length=64)["serve_kv"]
    assert tp_sk["kv_shards"] == 2
    assert tp_sk["bytes_per_page_per_chip"] == sk["bytes_per_page"] // 2
    assert tp_sk["bytes_per_slot_per_chip_at_seq"] == \
        sk["bytes_per_slot_at_seq"] // 2

    # MoE configs get the dispatch-transient pricing (dense-vs-ragged bytes)
    moe_t = Trainer(bundle=get_model("moe-debug", dtype=jnp.float32),
                    optimizer=adamw_cosine(1e-3),
                    plan=make_plan("ep", make_mesh(ep=8)), donate=False)
    moe_rep = run_preflight(moe_t, global_batch=8, seq_length=64)
    md = moe_rep["moe_dispatch"]
    cfg = moe_t.bundle.config
    t_tok, k = 8 * 64, cfg.experts_per_token
    assert md["mode"] == "dense"
    assert md["per_layer_ragged_dispatch_bytes"] == (
        k * t_tok * (2 * cfg.hidden_size + cfg.intermediate_size) * 4)
    assert md["per_layer_dense_dispatch_bytes"] > 0
    assert md["dense_over_ragged"] == pytest.approx(
        md["per_layer_dense_dispatch_bytes"]
        / md["per_layer_ragged_dispatch_bytes"], rel=0.01)

    total_param_bytes = sum(
        np.prod(l.shape) * l.dtype.itemsize
        for l in jax.tree.leaves(t.param_shapes))
    # fsdp shards most leaves 8-ways; small replicated leaves (norms) mean
    # per-device sits between total/8 and total
    assert total_param_bytes / 8 <= rep["per_device_param_bytes"] < total_param_bytes
    # fp32 Adam: mu + nu ~= 2x the param bytes, same shardings
    assert 1.8 * rep["per_device_param_bytes"] < rep["per_device_opt_state_bytes"] \
        < 2.2 * rep["per_device_param_bytes"] + 4096
