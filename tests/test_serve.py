"""Serving engine correctness: continuous-batching output must be
token-identical to the batch-1 sampler whatever the admission order,
co-residency, or slot reuse; KV residency must scale with allocated pages;
backpressure must refuse admission without corrupting running sequences.

Everything here runs debug-size models (2 layers, 64 wide) — each engine
is a handful of tiny compiles, so the suite stays inside tier-1.
"""
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.models.sample import make_sampler
from distributed_training_guide_tpu.serve import (Request, ServeEngine,
                                                  kv_page_bytes)
from distributed_training_guide_tpu.serve.api import (generate_many,
                                                      serve_http,
                                                      throughput_stats)

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


def _batch1(bundle, params, prompt, steps):
    """The batch-1 kv-cache reference (= the engine at n_slots=1, which
    test_sample.py pins against the independent full-recompute sampler)."""
    return make_sampler(bundle, kv_cache=True)(params, prompt, steps)


# ---- order invariance / continuous batching parity -------------------------

@pytest.mark.parametrize("name", ["llama-debug", "gpt2-debug", "moe-debug"])
def test_engine_matches_batch1_under_continuous_batching(name):
    """8 requests of different lengths through 3 slots: co-residency,
    eviction mid-flight, slot reuse — every request's tokens must equal its
    own batch-1 generation, in BOTH admission orders."""
    over = {"capacity_factor": 4.0} if name == "moe-debug" else {}
    bundle = get_model(name, dtype=jnp.float32, **over)
    params = bundle.init(bundle.config, jax.random.key(0))
    reqs = [Request(prompt_ids=[3 + i, 17, 42][:(i % 3) + 1],
                    max_new_tokens=3 + (i % 4), seed=i) for i in range(8)]
    expect = {i: _batch1(bundle, params, r.prompt_ids, r.max_new_tokens)
              for i, r in enumerate(reqs)}

    for order in (list(range(8)), [5, 2, 7, 0, 3, 6, 1, 4]):
        eng = ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=16)
        res = generate_many(eng, [reqs[i] for i in order])
        for pos, i in enumerate(order):
            assert res[pos].token_ids == expect[i], (
                f"{name}: request {i} diverged when admitted at {pos}")


def test_engine_matches_independent_recompute_reference(llama):
    """Close the loop on the delegation: multi-slot engine output equals
    the FULL-RECOMPUTE sampler (a genuinely independent program — no kv
    cache, no paging), not just the batch-1 engine."""
    bundle, params = llama
    reqs = [Request(prompt_ids=[3, 17, 42, 7], max_new_tokens=6),
            Request(prompt_ids=[5, 6], max_new_tokens=8)]
    res = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32),
        reqs)
    for r in res:
        assert r.token_ids == make_sampler(bundle)(
            params, r.prompt_ids, len(r.generated_ids))


def test_temperature_stream_is_admission_order_invariant(llama):
    """Sampling keys are fold_in(seed, position): a stochastic request
    draws the same tokens whichever slot/iteration it lands in."""
    bundle, params = llama
    reqs = [Request(prompt_ids=[3, 17], max_new_tokens=6, temperature=0.9,
                    top_k=40, top_p=0.9, seed=7),
            Request(prompt_ids=[9, 2, 5], max_new_tokens=6, temperature=0.7,
                    seed=8),
            Request(prompt_ids=[4], max_new_tokens=4, temperature=1.3,
                    seed=9)]
    a = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16),
        reqs)
    b = generate_many(
        ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=16),
        list(reversed(reqs)))
    for i in range(3):
        assert a[i].token_ids == b[2 - i].token_ids
    v = bundle.config.vocab_size
    assert all(0 <= t < v for r in a for t in r.generated_ids)


# ---- slot lifecycle ---------------------------------------------------------

def test_eos_evicts_early_and_frees_the_slot(llama):
    """Set eos to a token the greedy run is known to emit mid-stream: the
    engine must stop there (finish_reason="eos", eos included), free the
    slot, and the queued request behind it must still match batch-1."""
    bundle, params = llama
    prompt = [3, 17, 42, 7]
    full = _batch1(bundle, params, prompt, 6)
    eos = full[len(prompt) + 2]               # greedy emits it as token #3
    reqs = [Request(prompt_ids=prompt, max_new_tokens=6, eos_id=eos),
            Request(prompt_ids=[5, 6], max_new_tokens=8),
            Request(prompt_ids=[9, 2], max_new_tokens=4)]
    eng = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=16)
    res = generate_many(eng, reqs)
    assert res[0].finish_reason == "eos"
    assert res[0].token_ids == full[:len(prompt) + 3]
    assert res[1].finish_reason == "length"
    assert res[1].token_ids == _batch1(bundle, params, [5, 6], 8)
    assert res[2].token_ids == _batch1(bundle, params, [9, 2], 4)
    # every page reference was released: free + prefix-cache-retained ==
    # capacity (the full prompt page of request 0 stays cached for reuse)
    pool = eng.scheduler.pool
    assert pool.n_free + eng.scheduler.cache_pages_held() == pool.capacity
    assert eng.scheduler.cache_pages_held() == 1   # [3, 17, 42, 7] page


def test_backpressure_refuses_admission_never_corrupts(llama):
    """Pool sized well below the workload's worst case: optimistic
    admission over-admits, growth exhausts the pool, the youngest
    sequences are preempted and recomputed — and every request still
    finishes byte-identical to batch-1, with the pressure visible in the
    blocked/preempted stats and no page leaked at the end."""
    bundle, params = llama
    # each request: 3 prompt + 5 new = 8 tokens = 2 pages of 4; the pool's
    # 3 usable pages cannot hold three such sequences at once
    eng = ServeEngine(bundle, params, n_slots=4, page_size=4, max_len=8,
                      n_pages=4)
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=5, seed=i)
            for i in range(3)]
    res = generate_many(eng, reqs, max_iterations=500)
    for r in res:
        assert r.token_ids == _batch1(bundle, params, r.prompt_ids, 5)
    stats = eng.scheduler.stats
    assert stats["admission_blocked"] + stats["preempted"] > 0
    pool = eng.scheduler.pool
    assert pool.n_free + eng.scheduler.cache_pages_held() == pool.capacity


def test_impossible_request_refused_at_submit(llama):
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                      n_pages=3)
    with pytest.raises(ValueError, match="whole pool"):
        eng.submit(Request(prompt_ids=[1, 2, 3], max_new_tokens=10))
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(Request(prompt_ids=[1] * 10, max_new_tokens=10))
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit(Request(prompt_ids=[]))


def test_unservable_configs_refused_up_front(llama):
    """Requests/configs that would crash mid-flight (seed past int32,
    buckets that can't cover an admissible prompt) must refuse at submit /
    construction, before any slot or page is committed."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=16)
    with pytest.raises(ValueError, match="seed"):
        eng.submit(Request(prompt_ids=[1], seed=2 ** 31))
    with pytest.raises(ValueError, match="top_k"):
        eng.submit(Request(prompt_ids=[1], top_k=2 ** 31))
    with pytest.raises(ValueError, match="vocab_size"):
        eng.submit(Request(prompt_ids=[bundle.config.vocab_size]))
    with pytest.raises(ValueError, match="cover"):
        ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=32,
                    prefill_buckets=(4, 8))
    with pytest.raises(ValueError, match="capacity"):
        ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=16,
                    prefill_buckets=(64,))


def test_engine_thread_death_fails_waiters_loudly(llama, monkeypatch):
    """If the engine thread hits an unexpected error, pending HTTP waiters
    get a 500 (not an eternal hang), /healthz flips unhealthy, and new
    submits are refused with 503."""
    import http.client
    import json
    import time as _t

    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=16)

    def boom(*a, **k):
        raise RuntimeError("injected engine fault")

    monkeypatch.setattr(eng, "step", boom)
    server, worker = serve_http(eng, port=0)
    port = server.server_address[1]
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/generate",
                     json.dumps({"prompt_ids": [3], "max_new_tokens": 2}))
        resp = conn.getresponse()
        assert resp.status == 500
        assert "injected engine fault" in json.loads(resp.read())["error"]
        deadline = _t.monotonic() + 10
        while worker.dead is None and _t.monotonic() < deadline:
            _t.sleep(0.01)
        conn.request("GET", "/healthz")
        assert json.loads(conn.getresponse().read())["ok"] is False
        conn.request("POST", "/generate",
                     json.dumps({"prompt_ids": [3], "max_new_tokens": 2}))
        assert conn.getresponse().status == 503
        conn.close()
    finally:
        server.shutdown()
        worker.stop()


def test_throughput_stats_shape(llama):
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16)
    import time as _t

    t0 = _t.perf_counter()
    res = generate_many(eng, [Request(prompt_ids=[3, 17], max_new_tokens=4,
                                      seed=s) for s in range(2)])
    stats = throughput_stats(res, _t.perf_counter() - t0, eng)
    assert stats["generated_tokens"] == 8
    assert stats["tokens_per_s"] > 0
    assert 0 < stats["decode_occupancy"] <= 1.0
    assert stats["n_requests"] == 2


# ---- memory pin -------------------------------------------------------------

def test_kv_residency_scales_with_pages_not_slots_times_maxlen(llama):
    """The acceptance-criteria pin. (a) live buffers: the engine's resident
    KV bytes equal the page-pool formula and sit well under the dense
    n_slots x max_len cache; (b) lowered HLO: the compiled decode step's
    cache operands/results ARE the pool shape — the program carries no
    [n_slots, max_len] resident cache."""
    bundle, params = llama
    cfg = bundle.config
    n_slots, page, max_len = 8, 16, 256
    # pool sized at 1/4 of full residency: 32 usable pages + trash
    eng = ServeEngine(bundle, params, n_slots=n_slots, page_size=page,
                      max_len=max_len, n_pages=33)

    assert eng.kv_cache_bytes() == kv_page_bytes(cfg, page_size=page,
                                                 n_pages=33)
    from distributed_training_guide_tpu.models import llama as llama_mod

    dense = llama_mod.init_cache(cfg, n_slots, max_len)
    dense_bytes = dense["k"].nbytes + dense["v"].nbytes
    assert eng.kv_cache_bytes() < dense_bytes / 3.5

    # (b) lower the ONE decode program and inspect its kv operands
    arr = eng.scheduler.decode_arrays()
    lowered = eng._decode_fn.lower(
        eng.params, eng.pages["k"], eng.pages["v"],
        jnp.asarray(arr["tokens"]), jnp.asarray(arr["lengths"]),
        jnp.asarray(arr["tables"]), jnp.asarray(arr["seeds"]),
        jnp.asarray(arr["temps"]), jnp.asarray(arr["top_ks"]),
        jnp.asarray(arr["top_ps"]), jnp.asarray(arr["actives"]))
    pool_shape = (cfg.num_layers, 33, page, cfg.num_kv_heads, cfg.head_size)
    avals = jax.tree.leaves(lowered.in_avals)
    assert sum(a.shape == pool_shape for a in avals) == 2   # k and v pools
    dense_shape = (cfg.num_layers, n_slots, max_len, cfg.num_kv_heads,
                   cfg.head_size)
    assert not any(a.shape == dense_shape for a in avals)
    out_avals = jax.tree.leaves(lowered.out_info)
    assert sum(tuple(a.shape) == pool_shape for a in out_avals) == 2

    # the under-provisioned pool still serves (backpressure, not OOM): 8
    # co-resident 40-token requests would need 8x3=24 pages of the 32
    reqs = [Request(prompt_ids=[3 + i, 5], max_new_tokens=38, seed=i)
            for i in range(8)]
    res = generate_many(eng, reqs)
    assert all(len(r.generated_ids) == 38 for r in res)


# ---- prefix sharing / copy-on-write ----------------------------------------

def _drain(eng, max_iters=3000):
    """Step the engine until idle, collecting every finished result."""
    out, it = [], 0
    while eng.has_work:
        out.extend(eng.step())
        it += 1
        assert it < max_iters, "engine stalled"
    return out


def _ref_engine(bundle, params, **kw):
    """A fresh batch-1 reference engine (no sharing — the independent
    baseline every feature must match token-for-token)."""
    return ServeEngine(bundle, params, n_slots=1, prefix_cache=False, **kw)


def _fresh(req):
    """A copy of the request without its assigned id (re-submittable)."""
    import dataclasses

    return dataclasses.replace(req, request_id=None)


def test_prefix_sharing_same_physical_pages_and_bytes(llama):
    """The acceptance pin: slots sharing a 2-page prefix hold refcounted
    references to the SAME physical pages; resident pages for n co-liers
    beat unshared by exactly the (n-1) * shared_pages the formula
    predicts; and everything still matches batch-1."""
    bundle, params = llama
    common = [9, 8, 7, 6, 5, 4, 3, 2]          # 2 full shared pages
    eng = ServeEngine(bundle, params, n_slots=4, page_size=4, max_len=32)
    # seed the cache: one request commits + registers the common prefix
    generate_many(eng, [Request(prompt_ids=common + [10], max_new_tokens=2)])
    assert eng.scheduler.cache_pages_held() == 2
    pool = eng.scheduler.pool
    base_used = pool.capacity - pool.n_free

    reqs = [Request(prompt_ids=common + [11 + i], max_new_tokens=8, seed=i)
            for i in range(4)]
    rids = [eng.submit(r) for r in reqs]
    eng.step()                                  # admit + prefill all four
    slots = [s for s in eng.scheduler.slots if s is not None]
    assert len(slots) == 4
    assert len({tuple(s.pages[:2]) for s in slots}) == 1, \
        "shared prefix must map to one physical page pair"
    for p in slots[0].pages[:2]:
        assert pool.refcount(p) == 5            # 4 slots + the cache
    # each 9-token prompt worst-cases 3 pages; with sharing the four
    # sequences added ONE private page each instead of three
    assert (pool.capacity - pool.n_free) - base_used == 4

    done = {r.request_id: r for r in _drain(eng)}
    stats = eng.scheduler.stats
    assert stats["prefix_hits"] >= 4
    assert stats["prefix_tokens_shared"] >= 4 * len(common)
    for rid, r in zip(rids, reqs):
        assert done[rid].token_ids == _batch1(bundle, params,
                                              r.prompt_ids, 8)
    assert pool.n_free + eng.scheduler.cache_pages_held() == pool.capacity


def test_cow_fork_on_mid_page_divergence(llama):
    """A prompt that diverges INSIDE a registered page (chunked mode
    unlocks mid-page reuse) forks that page copy-on-write: the fork stat
    fires, the shared source page keeps serving its original content, and
    both outputs stay token-identical to batch-1."""
    bundle, params = llama
    common8 = [9, 8, 7, 6, 5, 4, 3, 2]
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32,
                      prefill_chunk=4)
    resA = generate_many(eng, [Request(prompt_ids=common8 + [1],
                                       max_new_tokens=3)])
    promptB = common8[:6] + [99]               # diverges in page 2
    resB = generate_many(eng, [Request(prompt_ids=promptB,
                                       max_new_tokens=5)])
    stats = eng.scheduler.stats
    assert stats["cow_forks"] == 1
    assert stats["prefix_tokens_shared"] >= 6  # 4 aligned + 2 into page 2
    assert resA[0].token_ids == _batch1(bundle, params, common8 + [1], 3)
    assert resB[0].token_ids == _batch1(bundle, params, promptB, 5)
    # the registered original still matches after the fork wrote nothing
    # into its page: a third request re-using the FULL original prefix
    resC = generate_many(eng, [Request(prompt_ids=common8 + [1],
                                       max_new_tokens=3)])
    assert resC[0].token_ids == resA[0].token_ids


def test_admission_eviction_cannot_stale_matched_prefix():
    """Regression pin: try_admit takes its share references on matched
    prefix pages BEFORE allocation pressure runs — cache eviction during
    the same admission must never hand a matched page back out as the
    slot's own private page (double-use) or crash sharing a dead node.
    Driven at the scheduler level with a pool squeezed to exactly the
    triggering state: cache-only refs + zero free pages."""
    from distributed_training_guide_tpu.serve import PagePool, Scheduler

    pool = PagePool(n_pages=4, page_size=4)          # 3 usable
    sched = Scheduler(n_slots=2, pool=pool, max_len=16,
                      max_pages_per_slot=4, prefix_cache=True)
    cached = pool.alloc(2)
    sched.cache.register(list(range(1, 9)), cached)  # 2 full pages
    pool.free(cached)                                # cache-only refs now
    [dummy] = pool.alloc(1)                          # free list: empty
    assert pool.n_free == 0

    sched.submit(Request(prompt_ids=list(range(1, 10)), max_new_tokens=2))
    adms = sched.try_admit()
    # matched pages' nodes are the only evictable thing; with the refs
    # taken first the eviction cannot free them, so the head must BLOCK
    # cleanly (not double-issue a matched page)
    assert adms == []
    assert sched.stats["admission_blocked"] == 1
    for slot in sched.slots:
        assert slot is None
    # releasing the unrelated page unblocks; the slot's pages are distinct
    pool.free([dummy])
    adms = sched.try_admit()
    assert len(adms) == 1
    pages = sched.slots[adms[0].slot_idx].pages
    assert len(set(pages)) == len(pages) == 3


# ---- preemption-by-recompute ------------------------------------------------

def test_preemption_recompute_token_identity(llama):
    """Chaos-style pressure: a pool far below the worst case forces
    preemptions (visible in stats); every request — greedy AND sampled —
    still returns tokens identical to the batch-1 engine, and the pool
    balances to zero leaked references."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=4, page_size=4, max_len=16,
                      n_pages=7)
    reqs = [Request(prompt_ids=[3 + i, 17, 42][:1 + i % 3],
                    max_new_tokens=6 + (i % 5),
                    temperature=0.8 if i % 2 else 0.0, seed=i)
            for i in range(8)]
    res = generate_many(eng, reqs, max_iterations=3000)
    assert eng.scheduler.stats["preempted"] > 0
    ref_eng = _ref_engine(bundle, params, page_size=4, max_len=16)
    for got, req in zip(res, reqs):
        ref = generate_many(ref_eng, [_fresh(req)])[0]
        assert got.token_ids == ref.token_ids, \
            f"request seed={req.seed} diverged across preemption"
    pool = eng.scheduler.pool
    assert pool.n_free + eng.scheduler.cache_pages_held() == pool.capacity


def _cache_page_refs(sched) -> dict:
    """page -> number of prefix-cache references (one per node)."""
    refs: dict = {}
    if sched.cache is None:
        return refs
    stack = [sched.cache.root]
    while stack:
        node = stack.pop()
        for child in node.children.values():
            refs[child.page] = refs.get(child.page, 0) + 1
            stack.append(child)
    return refs


@pytest.mark.parametrize(
    "kv_dtype,weight_dtype",
    [(None, None),
     pytest.param("int8", None, marks=pytest.mark.kvquant),
     pytest.param(None, "int8", marks=pytest.mark.wquant)],
    ids=["fp32", "kv-int8", "w-int8"])
def test_scheduler_random_trace_invariants(llama, kv_dtype, weight_dtype):
    """Property-style trace over refcounted CoW pages: random
    submit/step events on a tight pool with chunked prefill, asserting
    after EVERY iteration that (a) page refcounts equal the number of
    holders (slots + cache nodes), (b) the trash page never enters a live
    table, (c) free + held pages balance to capacity, and (d) every
    completed request is token-identical to its batch-1 run. Re-run with
    the int8-quantized pool (the kvquant satellite): the allocator never
    sees dtypes, but the DEVICE side does — preempt/replay/CoW/commit all
    rewrite quantized bytes + scales, and the batch-1 oracle (itself
    int8) pins that those rewrites are bitwise. The THIRD run is the
    wquant satellite — int8 WEIGHTS over an fp32 pool: every program
    (prefill, decode, replay) reads the same quantized params, so the
    invariants and the batch-1 oracle must hold unchanged."""
    bundle, params = llama
    rng = np.random.default_rng(42)
    eng = ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=16,
                      n_pages=7, prefill_chunk=4, kv_dtype=kv_dtype,
                      weight_dtype=weight_dtype)
    sched, pool = eng.scheduler, eng.scheduler.pool
    done, submitted = [], []
    for it in range(400):
        if rng.random() < 0.3 and len(submitted) < 20:
            n_prompt = int(rng.integers(1, 10))
            req = Request(
                prompt_ids=[int(rng.integers(3, 500))
                            for _ in range(n_prompt)],
                max_new_tokens=int(rng.integers(4, 17 - n_prompt)),
                temperature=float(rng.choice([0.0, 0.9])),
                seed=len(submitted))
            submitted.append((eng.submit(req), req))
        done.extend(eng.step())

        held: dict = {}
        for slot in sched.slots:
            if slot is None:
                continue
            assert 0 not in slot.pages, "trash page in a live table"
            assert len(set(slot.pages)) == len(slot.pages)
            assert slot.cache_len <= len(slot.pages) * eng.page_size
            for p in slot.pages:
                held[p] = held.get(p, 0) + 1
        for p, n in _cache_page_refs(sched).items():
            held[p] = held.get(p, 0) + n
        for p, n in held.items():
            assert pool.refcount(p) == n, \
                f"page {p}: {n} holders but refcount {pool.refcount(p)}"
            assert p not in pool._free_set
        assert pool.n_free + len(held) == pool.capacity
        if len(done) == len(submitted) and not eng.has_work and it > 100:
            break
    done.extend(_drain(eng))
    assert len(done) == len(submitted)
    assert sched.stats["preempted"] > 0        # the trace hit real pressure
    by_id = {r.request_id: r for r in done}
    # the int8 oracle must share the PREFILL MODE: chunked prefill
    # attends over already-quantized history while a bucket prefill
    # computes the whole prompt in float and quantizes once at commit —
    # under fp32 the two agree to ~1e-7 (never flips this trace), under
    # int8 that difference is a 1-LSB cache rounding that can. Token
    # identity is program-relative, and the scheduling-invariance claim
    # is engine-config-relative — so the reference runs the same chunk
    # program (see serve/kv_pages.py docstring).
    ref_eng = _ref_engine(bundle, params, page_size=4, max_len=16,
                          kv_dtype=kv_dtype, weight_dtype=weight_dtype,
                          prefill_chunk=4 if kv_dtype == "int8" else None)
    for rid, req in submitted:
        ref = generate_many(ref_eng, [_fresh(req)])[0]
        assert by_id[rid].token_ids == ref.token_ids


# ---- property traces over the grown surface (PR 9) -------------------------
# The scheduler invariant — refuse or cleanly preempt/evict, never corrupt
# — must survive every extension: streaming taps, deadlines, priorities,
# the sharded pool, and the disaggregated handoff. Random traces assert
# after EVERY iteration that (a) each page's refcount equals its holder
# count, (b) free + held + cache-only pages balance to capacity, (c) the
# trash page never enters a live table, and at the end that every
# completion is token-identical to batch-1 (deadline evictions: a strict
# prefix).


def _pool_invariants(pool, holder_maps):
    """holder_maps: iterables of {page: n_refs}. Assert refcount==holders
    and the capacity identity."""
    held: dict = {}
    for m in holder_maps:
        for p, n in m.items():
            held[p] = held.get(p, 0) + n
    for p, n in held.items():
        assert pool.refcount(p) == n, \
            f"page {p}: {n} holders but refcount {pool.refcount(p)}"
        assert p not in pool._free_set
    assert pool.n_free + len(held) == pool.capacity


def _slot_holders(sched, page_size):
    held: dict = {}
    for slot in sched.slots:
        if slot is None:
            continue
        assert 0 not in slot.pages, "trash page in a live table"
        assert len(set(slot.pages)) == len(slot.pages)
        assert slot.cache_len <= len(slot.pages) * page_size
        for p in slot.pages:
            held[p] = held.get(p, 0) + 1
    return held


def _check_completions(bundle, params, done, submitted, *, max_len):
    """Every finished request equals batch-1; deadline evictions must be
    a strict prefix of the batch-1 generation (clean, never garbage).
    The reference runs with the deadline STRIPPED — it is the
    deadline-free baseline, and a cold-compile reference engine could
    otherwise itself expire a 'racing' deadline and corrupt the oracle."""
    import dataclasses

    ref_eng = _ref_engine(bundle, params, page_size=4, max_len=max_len)
    by_id = {r.request_id: r for r in done}
    for rid, req in submitted:
        res = by_id[rid]
        baseline = dataclasses.replace(_fresh(req), deadline_s=None)
        ref = generate_many(ref_eng, [baseline])[0]
        if res.finish_reason == "deadline":
            n = len(res.generated_ids)
            assert res.generated_ids == ref.generated_ids[:n], \
                f"seed={req.seed}: deadline eviction returned garbage"
        else:
            assert res.token_ids == ref.token_ids, \
                f"seed={req.seed} diverged"


def _random_request(rng, n_submitted):
    n_prompt = int(rng.integers(1, 10))
    dl = rng.random()
    return Request(
        prompt_ids=[int(rng.integers(3, 500)) for _ in range(n_prompt)],
        max_new_tokens=int(rng.integers(4, 17 - n_prompt)),
        temperature=float(rng.choice([0.0, 0.9])),
        priority=int(rng.integers(0, 3)),
        # a third guaranteed-expired, a third racing, a third unbounded
        deadline_s=(1e-6 if dl < 0.33 else
                    float(rng.uniform(0.01, 0.1)) if dl < 0.66 else None),
        seed=n_submitted)


@pytest.mark.stream
def test_random_trace_stream_deadline_priority_sharded(llama,
                                                       eight_devices):
    """The grown monolith under pressure AND the sharded pool: random
    submits with priorities + deadlines, the streaming tap read every
    iteration (its prefixes must match the final tokens), pool
    invariants after every step, completions vs batch-1."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    bundle, params = llama
    plan = make_plan("tp", make_mesh(tp=2, devices=eight_devices[:2]))
    rng = np.random.default_rng(7)
    eng = ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=16,
                      n_pages=8, prefill_chunk=4, plan=plan, shard_kv=True)
    sched, pool = eng.scheduler, eng.scheduler.pool
    done, submitted, streamed = [], [], {}
    for it in range(250):
        if rng.random() < 0.3 and len(submitted) < 14:
            req = _random_request(rng, len(submitted))
            submitted.append((eng.submit(req), req))
        done.extend(eng.step())
        for rid, toks in eng.partial_tokens().items():
            prev = streamed.get(rid, [])
            assert toks[:len(prev)] == prev, "stream rewrote history"
            streamed[rid] = toks
        _pool_invariants(pool, [_slot_holders(sched, eng.page_size),
                                _cache_page_refs(sched)])
        if len(done) == len(submitted) and not eng.has_work and it > 80:
            break
    done.extend(_drain(eng))
    assert len(done) == len(submitted)
    assert sched.stats["deadline_expired"] > 0
    _check_completions(bundle, params, done, submitted, max_len=16)
    # streamed prefixes of completed requests match their final tokens
    by_id = {r.request_id: r for r in done}
    for rid, toks in streamed.items():
        assert by_id[rid].generated_ids[:len(toks)] == toks


@pytest.mark.disagg
def test_random_trace_disagg_handoff(llama):
    """The disaggregated pair under pressure: the same trace with the
    handoff in the holder accounting — a page in transit (released by
    the prefill scheduler, not yet adopted) is still exactly one
    reference. Preempt-requeue-replay must keep token identity."""
    bundle, params = llama
    rng = np.random.default_rng(11)
    from distributed_training_guide_tpu.serve.disagg import DisaggEngine

    eng = DisaggEngine(bundle, params, n_slots=3, n_prefill_slots=2,
                       page_size=4, max_len=16, n_pages=9,
                       prefill_chunk=4)
    done, submitted = [], []
    for it in range(400):
        if rng.random() < 0.3 and len(submitted) < 16:
            req = _random_request(rng, len(submitted))
            submitted.append((eng.submit(req), req))
        done.extend(eng.step())
        transit: dict = {}
        for h in eng.handoff.pending:
            assert 0 not in h.pages
            for p in h.pages:
                transit[p] = transit.get(p, 0) + 1
        _pool_invariants(eng.pool, [
            _slot_holders(eng.prefill.sched, eng.page_size),
            _slot_holders(eng.decode.sched, eng.page_size),
            transit, _cache_page_refs(eng.prefill.sched)])
        if len(done) == len(submitted) and not eng.has_work and it > 100:
            break
    done.extend(_drain(eng))
    assert len(done) == len(submitted)
    stats = eng.stats()
    assert stats["deadline_expired"] > 0
    assert stats["handoff_transfers"] > 0
    assert stats["handoff_bytes_copied"] == 0
    _check_completions(bundle, params, done, submitted, max_len=16)


# ---- chunked prefill --------------------------------------------------------

def test_chunked_prefill_interleaves_with_resident_decode(llama):
    """A long prompt fed in fixed-budget chunks must NOT stall a resident
    decode: the short request keeps generating while the long prompt
    streams in (~ceil(prompt/chunk) bounded iterations), and both match
    batch-1."""
    bundle, params = llama
    chunk = 8
    long_prompt = [3 + (i % 200) for i in range(60)]
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=128,
                      prefill_chunk=chunk)
    short = Request(prompt_ids=[5, 6], max_new_tokens=24, seed=1)
    rid_short = eng.submit(short)
    eng.step()                                 # short is decoding
    long_req = Request(prompt_ids=long_prompt, max_new_tokens=4, seed=2)
    rid_long = eng.submit(long_req)

    results = []
    iters_while_prefilling = 0
    short_tokens_during = 0
    it = 0
    while eng.has_work:
        s0 = eng.scheduler.slots[0]
        before = len(s0.generated) if s0 else None
        prefilling = any(s is not None and s.prefilling
                         for s in eng.scheduler.slots)
        results.extend(eng.step())
        if prefilling:
            iters_while_prefilling += 1
            s0 = eng.scheduler.slots[0]
            after = len(s0.generated) if s0 else before
            if before is not None and after is not None:
                short_tokens_during += after - before
        it += 1
        assert it < 500
    # the 60-token prompt needs ceil(60/8) = 8 chunk iterations (the first
    # rides the admission step, before the pre-step prefilling probe sees
    # it); the resident decode advanced through them instead of stalling
    # for one monolithic prefill
    assert iters_while_prefilling >= 7
    assert short_tokens_during >= 6

    by_id = {r.request_id: r for r in results}
    ref_eng = _ref_engine(bundle, params, page_size=4, max_len=128)
    for rid, req in ((rid_short, short), (rid_long, long_req)):
        ref = generate_many(ref_eng, [_fresh(req)])[0]
        assert by_id[rid].token_ids == ref.token_ids


@pytest.mark.parametrize("name", ["gpt2-debug", "neox-debug", "moe-debug"])
def test_chunked_prefill_across_families(name):
    """The multi-token chunk path exercises family-specific machinery
    (gpt2's learned position rows, neox's parallel residual, moe's routed
    FFN over T tokens) — chunked output must equal the bucketed engine's
    for each."""
    over = {"capacity_factor": 4.0} if name == "moe-debug" else {}
    bundle = get_model(name, dtype=jnp.float32, **over)
    params = bundle.init(bundle.config, jax.random.key(0))
    reqs = [Request(prompt_ids=[3 + i, 17, 42, 9, 11, 2, 8][:3 + i],
                    max_new_tokens=4, seed=i) for i in range(3)]
    chunked = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                    prefill_chunk=3), [_fresh(r) for r in reqs])
    bucketed = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16),
        [_fresh(r) for r in reqs])
    for a, b in zip(chunked, bucketed):
        assert a.token_ids == b.token_ids


# ---- sharded weights --------------------------------------------------------

def test_engine_runs_on_tp_mesh(llama, eight_devices):
    """Sharded weights through the existing plans: tp=2 params, replicated
    pages — tokens must match the single-device engine exactly."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    bundle, params = llama
    plan = make_plan("tp", make_mesh(tp=2, devices=eight_devices[:2]))
    reqs = [Request(prompt_ids=[3, 17, 42], max_new_tokens=5, seed=1),
            Request(prompt_ids=[5, 6], max_new_tokens=6, seed=2)]
    sharded = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                    plan=plan), reqs)
    single = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16),
        reqs)
    for a, b in zip(sharded, single):
        assert a.token_ids == b.token_ids


# ---- HTTP endpoint ----------------------------------------------------------

def test_http_endpoint_concurrent_requests(llama):
    """Two clients hitting the endpoint concurrently co-batch in the
    engine thread; responses carry tokens + latency and match batch-1."""
    import http.client
    import json

    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16)
    server, worker = serve_http(eng, port=0)
    port = server.server_address[1]
    try:
        payloads = [{"prompt_ids": [3, 17, 42], "max_new_tokens": 5},
                    {"prompt_ids": [5, 6], "max_new_tokens": 6}]
        out = [None, None]

        def post(i):
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
            conn.request("POST", "/generate", json.dumps(payloads[i]))
            resp = conn.getresponse()
            out[i] = (resp.status, json.loads(resp.read()))
            conn.close()

        threads = [threading.Thread(target=post, args=(i,)) for i in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        for i, payload in enumerate(payloads):
            status, body = out[i]
            assert status == 200
            assert body["token_ids"] == _batch1(
                bundle, params, payload["prompt_ids"],
                payload["max_new_tokens"])
            assert body["finish_reason"] == "length"
            assert body["latency_s"] >= 0

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        assert health["ok"] and health["n_slots"] == 2
        conn.request("POST", "/generate", json.dumps({"prompt_ids": []}))
        assert conn.getresponse().status == 400   # scheduler refusal -> 400
        conn.close()
    finally:
        server.shutdown()
        worker.stop()


def test_serve_cli_offline_batch(capsys):
    """python -m distributed_training_guide_tpu.serve hermetic path: one
    JSON line per request + the aggregate stats line."""
    import json

    from distributed_training_guide_tpu.serve.__main__ import main

    main(["-m", "llama-debug", "--prompt-ids", "3,17,42",
          "--prompt-ids", "5,6", "--steps", "4", "--n-slots", "2",
          "--page-size", "4", "--max-len", "16"])
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    assert "kv_report" in lines[0]
    results = [l for l in lines if "token_ids" in l]
    assert len(results) == 2
    assert all(len(r["token_ids"]) == len(p) + 4
               for r, p in zip(results, ([3, 17, 42], [5, 6])))
    stats = lines[-1]["stats"]
    assert stats["n_requests"] == 2 and stats["generated_tokens"] == 8
