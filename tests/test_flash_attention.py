"""Pallas flash-attention numerics goldens vs the XLA reference path.

Runs the real kernels in interpreter mode on CPU (same code path the TPU
compiles), checking forward and all three gradients, with GQA and both
block-aligned and multi-block shapes.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.ops.attention import _xla_attention
from distributed_training_guide_tpu.ops.flash_attention import flash_attention


def make_qkv(b, s, hq, hkv, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (b, s, hq, d), dtype)
    k = jax.random.normal(ks[1], (b, s, hkv, d), dtype)
    v = jax.random.normal(ks[2], (b, s, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2), (8, 1)])
@pytest.mark.parametrize("s", [64, 128])
def test_forward_matches_xla(hq, hkv, s):
    q, k, v = make_qkv(2, s, hq, hkv, 32)
    ref = _xla_attention(q, k, v, causal=True, positions=None, kv_positions=None)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_noncausal_forward():
    q, k, v = make_qkv(1, 64, 2, 2, 32)
    ref = _xla_attention(q, k, v, causal=False, positions=None, kv_positions=None)
    out = flash_attention(q, k, v, causal=False, block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
def test_grads_match_xla(hq, hkv):
    q, k, v = make_qkv(1, 64, hq, hkv, 32, seed=1)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = _xla_attention(q, k, v, causal=True, positions=None, kv_positions=None)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name}")


def test_uneven_blocks():
    """seq not divisible by preferred block -> picker falls back."""
    q, k, v = make_qkv(1, 96, 2, 2, 32)
    ref = _xla_attention(q, k, v, causal=True, positions=None, kv_positions=None)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_forced_flash_rejects_untiled_shapes():
    """Compiled (non-interpret) flash with tile-indivisible shapes must fail
    loudly, not fall back to a full-sequence block (opaque Mosaic errors)."""
    q, k, v = make_qkv(1, 96, 2, 2, 32)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, causal=True, interpret=False)


def test_attn_remat_policy_through_flash_vjp():
    """The "attn" policy's checkpoint_name tags (flash_out / flash_lse,
    tagged inside the kernel's custom_vjp fwd) must survive jax.checkpoint:
    gradients under the policy match the un-remat'd ones. This is the bench
    headline configuration (remat_policy=attn + flash attention)."""
    from distributed_training_guide_tpu.train.step import REMAT_POLICIES

    q, k, v = make_qkv(1, 64, 4, 2, 32)

    def f(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                            interpret=True)
        return jnp.sum(o * o)  # nonlinear consumer: backward needs o itself

    ref = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(jax.checkpoint(f, policy=REMAT_POLICIES["attn"]),
                   argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(ref, got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)

    # numerics hold under ANY policy, so also pin the mechanism: with the
    # tags saved, backward runs 3 pallas_calls (dq + dkv + one fwd for the
    # primal output) vs 4 under full recompute (fwd re-run for residuals).
    # If a checkpoint_name tag drifts, the policy silently degrades to full
    # recompute and only this count catches it.
    def n_pallas(policy):
        jaxpr = jax.make_jaxpr(
            jax.grad(jax.checkpoint(f, policy=policy)))(q, k, v)
        return str(jaxpr).count("pallas_call")

    saved, recompute = n_pallas(REMAT_POLICIES["attn"]), n_pallas(REMAT_POLICIES["all"])
    assert saved < recompute, (saved, recompute)
