"""Pallas flash-attention numerics goldens vs the XLA reference path.

Runs the real kernels in interpreter mode on CPU (same code path the TPU
compiles), checking forward and all three gradients, with GQA and both
block-aligned and multi-block shapes.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.ops.attention import _xla_attention
from distributed_training_guide_tpu.ops.flash_attention import flash_attention


def make_qkv(b, s, hq, hkv, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (b, s, hq, d), dtype)
    k = jax.random.normal(ks[1], (b, s, hkv, d), dtype)
    v = jax.random.normal(ks[2], (b, s, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2), (8, 1)])
@pytest.mark.parametrize("s", [64, 128])
def test_forward_matches_xla(hq, hkv, s):
    q, k, v = make_qkv(2, s, hq, hkv, 32)
    ref = _xla_attention(q, k, v, causal=True, positions=None, kv_positions=None)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_noncausal_forward():
    q, k, v = make_qkv(1, 64, 2, 2, 32)
    ref = _xla_attention(q, k, v, causal=False, positions=None, kv_positions=None)
    out = flash_attention(q, k, v, causal=False, block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
def test_grads_match_xla(hq, hkv):
    q, k, v = make_qkv(1, 64, hq, hkv, 32, seed=1)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = _xla_attention(q, k, v, causal=True, positions=None, kv_positions=None)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name}")


def test_uneven_blocks():
    """seq not divisible by preferred block -> picker falls back."""
    q, k, v = make_qkv(1, 96, 2, 2, 32)
    ref = _xla_attention(q, k, v, causal=True, positions=None, kv_positions=None)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_forced_flash_rejects_untiled_shapes():
    """Compiled (non-interpret) flash with tile-indivisible shapes must fail
    loudly, not fall back to a full-sequence block (opaque Mosaic errors)."""
    q, k, v = make_qkv(1, 96, 2, 2, 32)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, causal=True, interpret=False)


def test_sharded_flash_partitions_instead_of_replicating(eight_devices):
    """GSPMD's fallback for the Mosaic custom call is gather-and-replicate;
    the shard_map wrapper must instead keep the kernel local: numerics match
    the dense reference AND the output/grad shardings keep their mesh axes
    (a replicated grad spec is exactly the failure being guarded)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distributed_training_guide_tpu.ops.flash_attention import (
        make_sharded_flash_attention)

    mesh = Mesh(np.array(eight_devices).reshape(2, 4), ("dp", "tp"))
    q, k, v = make_qkv(4, 128, 8, 4, 64, seed=2)
    attn = make_sharded_flash_attention(mesh, batch_axes=("dp",),
                                        head_axis="tp", forced=True)
    sh = NamedSharding(mesh, P("dp", None, "tp", None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

    @jax.jit
    def f(q, k, v):
        return jax.value_and_grad(
            lambda q: jnp.sum(attn(q, k, v).astype(jnp.float32) ** 2))(q)

    loss, grad = f(qs, ks, vs)
    ref = jax.value_and_grad(
        lambda q: jnp.sum(_xla_attention(q, k, v, True, None, None)
                          .astype(jnp.float32) ** 2))(q)
    np.testing.assert_allclose(float(loss), float(ref[0]), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref[1]),
                               rtol=2e-4, atol=2e-4)
    assert grad.sharding.spec == P("dp", None, "tp", None), grad.sharding
    # single-device meshes need no wrapper
    assert make_sharded_flash_attention(
        Mesh(np.array(eight_devices[:1]).reshape(1, 1), ("dp", "tp"))) is None
    # packed/non-contiguous layouts must fail loud (no positions reach the
    # callable, so a silent arange mask would be wrong)
    with pytest.raises(ValueError, match="contiguous"):
        attn(q, k, v, standard_layout=False)
    # batch not divisible by the manual axes: non-forced falls back to the
    # partitionable XLA path instead of crashing in shard_map
    attn_auto = make_sharded_flash_attention(mesh, batch_axes=("dp",),
                                             head_axis="tp", forced=False)
    q3, k3, v3 = make_qkv(3, 128, 8, 4, 64, seed=4)
    ref3 = _xla_attention(q3, k3, v3, True, None, None)
    np.testing.assert_allclose(np.asarray(attn_auto(q3, k3, v3)),
                               np.asarray(ref3), rtol=2e-4, atol=2e-4)


def test_trainer_forced_flash_matches_xla_on_sharded_plan(eight_devices):
    """End-to-end: a tp_fsdp train step with attn_impl='flash' (the sharded
    wrapper engages) reproduces the attn_impl='xla' losses."""
    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan
    from distributed_training_guide_tpu.train import Trainer, adamw_cosine

    def run(attn_impl):
        bundle = get_model("llama-debug")
        plan = make_plan("tp_fsdp", make_mesh(tp=2, fsdp=2))
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), plan=plan,
                    attn_impl=attn_impl, donate=False)
        state = t.init_state(0)
        ids = np.random.RandomState(3).randint(0, bundle.config.vocab_size,
                                               (4, 128))
        batch = {kk: jax.device_put(jnp.asarray(ids), t.batch_shardings()[kk])
                 for kk in ("input_ids", "labels")}
        losses = []
        for _ in range(3):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses

    np.testing.assert_allclose(run("flash"), run("xla"), rtol=2e-4)


def test_attn_remat_policy_through_flash_vjp():
    """The "attn" policy's checkpoint_name tags (flash_out / flash_lse,
    tagged inside the kernel's custom_vjp fwd) must survive jax.checkpoint:
    gradients under the policy match the un-remat'd ones. This is the bench
    headline configuration (remat_policy=attn + flash attention)."""
    from distributed_training_guide_tpu.train.step import REMAT_POLICIES

    q, k, v = make_qkv(1, 64, 4, 2, 32)

    def f(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                            interpret=True)
        return jnp.sum(o * o)  # nonlinear consumer: backward needs o itself

    ref = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(jax.checkpoint(f, policy=REMAT_POLICIES["attn"]),
                   argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(ref, got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)

    # numerics hold under ANY policy, so also pin the mechanism: with the
    # tags saved, backward runs 3 pallas_calls (dq + dkv + one fwd for the
    # primal output) vs 4 under full recompute (fwd re-run for residuals).
    # If a checkpoint_name tag drifts, the policy silently degrades to full
    # recompute and only this count catches it.
    def n_pallas(policy):
        jaxpr = jax.make_jaxpr(
            jax.grad(jax.checkpoint(f, policy=policy)))(q, k, v)
        return str(jaxpr).count("pallas_call")

    saved, recompute = n_pallas(REMAT_POLICIES["attn"]), n_pallas(REMAT_POLICIES["all"])
    assert saved < recompute, (saved, recompute)


def test_attn_remat_policy_through_sharded_wrapper(eight_devices):
    """Same mechanism pin for the SHARDED wrapper (the multi-chip path): the
    attn policy must save the tagged output + lse so backward runs 3 pallas
    calls, not 4. This regressed invisibly before: the fwd shard_map
    returned residual-only outputs (in-map transposes / kernel-layout o),
    and since a shard_map eqn is atomic under jax.checkpoint's partial-eval,
    rebuilding ANY of them re-ran the whole map — kernel included — making
    the policy silent full-recompute on every sharded mesh."""
    from jax.sharding import Mesh

    from distributed_training_guide_tpu.ops.flash_attention import (
        make_sharded_flash_attention)
    from distributed_training_guide_tpu.train.step import REMAT_POLICIES

    mesh = Mesh(np.array(eight_devices).reshape(8, 1), ("dp", "tp"))
    attn = make_sharded_flash_attention(mesh, batch_axes=("dp",),
                                        head_axis=None, forced=True)
    q, k, v = make_qkv(8, 64, 4, 2, 32, seed=7)

    def f(q, k, v):
        o = attn(q, k, v)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    ref = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(jax.checkpoint(f, policy=REMAT_POLICIES["attn"]),
                   argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(ref, got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)

    def n_pallas(policy):
        jaxpr = jax.make_jaxpr(
            jax.grad(jax.checkpoint(f, policy=REMAT_POLICIES[policy])))(q, k, v)
        return str(jaxpr).count("pallas_call")

    assert n_pallas("attn") < n_pallas("all"), \
        (n_pallas("attn"), n_pallas("all"))


# ---------------------------------------------------------------------------
# Gemma-2 attention extras: the {softcap, scale, window, per-layer windows}
# feature grid vs the XLA reference — fwd and all three grads, fp32
# interpret mode, GQA included. One combination per row so a regression
# names the feature that broke.
# ---------------------------------------------------------------------------

EXTRAS_GRID = [
    dict(logit_softcap=50.0),
    dict(scale=24.0 ** -0.5),
    dict(window=24),
    dict(logit_softcap=30.0, scale=24.0 ** -0.5),
    dict(logit_softcap=30.0, window=24),
    dict(logit_softcap=30.0, scale=24.0 ** -0.5, window=24),  # full Gemma-2
]


@pytest.mark.parametrize("extras", EXTRAS_GRID,
                         ids=lambda e: "+".join(sorted(e)))
@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
def test_attention_extras_fwd_and_grads_match_xla(extras, hq, hkv):
    from distributed_training_guide_tpu.ops.attention import (
        multihead_attention)

    q, k, v = make_qkv(1, 64, hq, hkv, 32, seed=3)

    def loss(attn_fn):
        def f(q, k, v):
            o = attn_fn(q, k, v)
            return jnp.mean(o * jnp.cos(o))
        return f

    def flash_fn(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                               interpret=True, **extras)

    def xla_fn(q, k, v):
        return multihead_attention(q, k, v, causal=True, impl="xla", **extras)

    out = flash_fn(q, k, v)
    ref = xla_fn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    g_flash = jax.grad(loss(flash_fn), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(xla_fn), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5, err_msg=f"d{name}")


def test_traced_window_matches_static_and_xla():
    """A TRACED window (Gemma-2's per-layer schedule rides a lax.scan) takes
    the dynamic-band operand path — it must match both the static-int band
    and the xla mask, fwd and grads, including the 2**30 'full attention
    this layer' encoding of window 0."""
    from distributed_training_guide_tpu.ops.attention import (
        multihead_attention)

    q, k, v = make_qkv(1, 64, 4, 2, 32, seed=4)

    @jax.jit
    def traced(q, k, v, w):
        return flash_attention(q, k, v, causal=True, window=w,
                               block_q=32, block_k=32, interpret=True)

    w = jnp.asarray(24, jnp.int32)
    static = flash_attention(q, k, v, causal=True, window=24,
                             block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(traced(q, k, v, w)),
                               np.asarray(static), rtol=1e-6, atol=1e-6)

    # grads through the dynamic band (the band's own cotangent is float0)
    def loss_traced(q, k, v):
        o = traced(q, k, v, w)
        return jnp.mean(o * o)

    def loss_xla(q, k, v):
        o = multihead_attention(q, k, v, causal=True, window=24, impl="xla")
        return jnp.mean(o * o)

    g_t = jax.grad(loss_traced, argnums=(0, 1, 2))(q, k, v)
    g_x = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_t, g_x):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5, err_msg=f"d{name}")

    # 2**30 = "full attention this layer" (_layer_window_column's encoding
    # of 0) degenerates to plain causal numerics
    full = traced(q, k, v, jnp.asarray(2 ** 30, jnp.int32))
    causal_ref = flash_attention(q, k, v, causal=True, block_q=32,
                                 block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(full), np.asarray(causal_ref),
                               rtol=1e-6, atol=1e-6)


def test_per_layer_window_scan_matches_unrolled():
    """The Gemma-2 shape of the plumbing: a window COLUMN riding lax.scan
    (one traced window per layer, softcap + scale active) must equal the
    per-layer unrolled static calls — the kernel grid sees one program, the
    band operand varies per scan step."""
    q, k, v = make_qkv(1, 64, 4, 2, 32, seed=5)
    extras = dict(scale=24.0 ** -0.5, logit_softcap=30.0)
    wins = jnp.asarray([24, 2 ** 30], jnp.int32)   # sliding, then full

    @jax.jit
    def scanned(q, k, v):
        def body(carry, w):
            o = flash_attention(q + carry, k, v, causal=True, window=w,
                                block_q=32, block_k=32, interpret=True,
                                **extras)
            return o, None
        out, _ = jax.lax.scan(body, jnp.zeros_like(q), wins)
        return out

    got = scanned(q, k, v)
    want = jnp.zeros_like(q)
    for w in (24, None):   # 2**30 == no band
        want = flash_attention(q + want, k, v, causal=True, window=w,
                               block_q=32, block_k=32, interpret=True,
                               **extras)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
