"""Native C++ token loader: build, determinism, content, resume."""
import numpy as np
import pytest

from distributed_training_guide_tpu.data.native_loader import (
    NativeTokenLoader, native_available, write_token_file)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def token_file(tmp_path_factory):
    data = np.arange(16 * 64, dtype=np.int32).reshape(16 * 64 // 8, 8)
    path = tmp_path_factory.mktemp("tok") / "tokens.bin"
    write_token_file(data, path)
    return path, data


def test_batches_cover_dataset(token_file):
    path, data = token_file
    loader = NativeTokenLoader(path, seq_len=8, batch=4, seed=7)
    assert len(loader) == len(data) // 4
    got = np.concatenate(list(loader.epoch_batches(epoch=0)))
    # every sequence appears exactly once (shuffled)
    assert sorted(map(tuple, got)) == sorted(map(tuple, data))
    loader.close()


def test_deterministic_and_epoch_reshuffle(token_file):
    path, _ = token_file
    l1 = NativeTokenLoader(path, seq_len=8, batch=4, seed=7)
    l2 = NativeTokenLoader(path, seq_len=8, batch=4, seed=7)
    a = list(l1.epoch_batches(epoch=0))
    b = list(l2.epoch_batches(epoch=0))
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    c = list(l1.epoch_batches(epoch=1))
    assert not all(np.array_equal(x, y) for x, y in zip(a, c))
    l1.close()
    l2.close()


def test_resume_mid_epoch(token_file):
    path, _ = token_file
    loader = NativeTokenLoader(path, seq_len=8, batch=4, seed=3)
    full = list(loader.epoch_batches(epoch=0))
    tail = list(loader.epoch_batches(epoch=0, start_step=3))
    for x, y in zip(full[3:], tail):
        np.testing.assert_array_equal(x, y)
    loader.close()
