"""Sharded page pool (serve/sharding.py): token identity of the
kv-head-sharded pool vs the replicated batch-1 reference, the compiled-HLO
pin that no chip holds a full-kv-head pool tensor, the rules-table
mechanics, and the construction-time contract checks.

All on llama-debug (4 q heads, 2 kv heads) over a tp=2 slice of the
virtual 8-device CPU mesh — the 2 kv heads split one per chip.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.serve import Request, ServeEngine
from distributed_training_guide_tpu.serve.api import generate_many
from distributed_training_guide_tpu.serve.sharding import (
    match_partition_rules, SERVE_KV_RULES)
from distributed_training_guide_tpu.utils import hlo as hlo_util

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


@pytest.fixture(scope="module")
def tp2_plan(eight_devices):
    return make_plan("tp", make_mesh(tp=2, devices=eight_devices[:2]))


def _fresh(req):
    return dataclasses.replace(req, request_id=None)


# ---- the rules table --------------------------------------------------------

def test_match_partition_rules_mechanics():
    """Pool leaves match the kv-head rule, bookkeeping arrays replicate,
    scalars replicate regardless, and an unmatched leaf fails loudly."""
    tree = {"pages": {"k": np.zeros((2, 5, 4, 2, 16)),
                      "v": np.zeros((2, 5, 4, 2, 16))},
            "tables": np.zeros((3, 4), np.int32),
            "temps": np.zeros(3, np.float32),
            "scalar": np.float32(1.0)}
    specs = match_partition_rules(SERVE_KV_RULES + ((r"scalar", P("tp")),),
                                  tree)
    assert specs["pages"]["k"] == P(None, None, None, "tp", None)
    assert specs["pages"]["v"] == P(None, None, None, "tp", None)
    assert specs["tables"] == P()
    assert specs["temps"] == P()
    assert specs["scalar"] == P()      # scalars never partition
    with pytest.raises(ValueError, match="no serve partition rule"):
        match_partition_rules(SERVE_KV_RULES,
                              {"mystery": np.zeros((4, 4))})


def test_shard_kv_contract_validated_at_construction(llama, eight_devices):
    """Every unservable sharded config refuses at engine construction:
    no plan, tp=1, a non-tp active axis, tp not dividing the kv heads."""
    bundle, params = llama
    with pytest.raises(ValueError, match="needs a plan"):
        ServeEngine(bundle, params, shard_kv=True)
    with pytest.raises(ValueError, match="tp > 1"):
        ServeEngine(bundle, params, shard_kv=True, plan=make_plan(
            "tp", make_mesh(devices=eight_devices[:1])))
    with pytest.raises(ValueError, match="tp-only"):
        ServeEngine(bundle, params, shard_kv=True, plan=make_plan(
            "tp_fsdp", make_mesh(tp=2, fsdp=2,
                                 devices=eight_devices[:4])))
    with pytest.raises(ValueError, match="num_kv_heads"):
        # llama-debug has 2 kv heads: tp=4 divides num_heads (4) only
        ServeEngine(bundle, params, shard_kv=True, plan=make_plan(
            "tp", make_mesh(tp=4, devices=eight_devices[:4])))


# ---- token identity ---------------------------------------------------------

def test_sharded_pool_token_identity(llama, tp2_plan):
    """The acceptance pin, first half: decode over per-chip pool slices
    is token-identical to the replicated single-device engine — greedy
    AND sampled, across co-residency and slot reuse."""
    bundle, params = llama
    reqs = [Request(prompt_ids=[3 + i, 17, 42][:(i % 3) + 1],
                    max_new_tokens=3 + (i % 4),
                    temperature=0.9 if i % 2 else 0.0, seed=i)
            for i in range(6)]
    sharded = generate_many(
        ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=16,
                    plan=tp2_plan, shard_kv=True),
        [_fresh(r) for r in reqs])
    single = generate_many(
        ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=16),
        [_fresh(r) for r in reqs])
    for a, b in zip(sharded, single):
        assert a.token_ids == b.token_ids


def test_sharded_chunked_prefill_and_cow(llama, tp2_plan):
    """Chunked prefill, prefix sharing, and the CoW fork all run their
    pool work inside the manual region: mid-page divergence under the
    sharded pool stays token-identical and forks exactly once."""
    bundle, params = llama
    common8 = [9, 8, 7, 6, 5, 4, 3, 2]
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32,
                      plan=tp2_plan, shard_kv=True, prefill_chunk=4)
    res_a = generate_many(eng, [Request(prompt_ids=common8 + [1],
                                        max_new_tokens=3)])
    prompt_b = common8[:6] + [99]
    res_b = generate_many(eng, [Request(prompt_ids=prompt_b,
                                        max_new_tokens=5)])
    assert eng.scheduler.stats["cow_forks"] == 1
    ref = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=32,
                      prefix_cache=False)
    assert res_a[0].token_ids == generate_many(
        ref, [Request(prompt_ids=common8 + [1], max_new_tokens=3)]
    )[0].token_ids
    assert res_b[0].token_ids == generate_many(
        ref, [Request(prompt_ids=prompt_b, max_new_tokens=5)])[0].token_ids
    pool = eng.scheduler.pool
    assert pool.n_free + eng.scheduler.cache_pages_held() == pool.capacity


@pytest.mark.flash_decode
def test_sharded_flash_kernel_parity(llama, tp2_plan):
    """The Pallas flash-decode kernel runs PER CHIP inside the manual
    region (interpret mode here — the point is the per-chip pool slice
    wiring, hkv_local=1): tokens must equal the replicated xla engine."""
    bundle, params = llama
    reqs = [Request(prompt_ids=[3, 17, 42], max_new_tokens=5, seed=1),
            Request(prompt_ids=[5, 6], max_new_tokens=4, seed=2)]
    flash = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=8, max_len=32,
                    plan=tp2_plan, shard_kv=True, attend_impl="flash"),
        [_fresh(r) for r in reqs])
    xla = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=8, max_len=32),
        [_fresh(r) for r in reqs])
    for a, b in zip(flash, xla):
        assert a.token_ids == b.token_ids


# ---- the HLO pin ------------------------------------------------------------

def test_sharded_pool_compiled_hlo_pin(llama, tp2_plan):
    """The acceptance pin, second half: the lowered+partitioned decode
    program's cache avals are the PER-CHIP pool shape (kvh/tp) — the
    full-kv-head pool tensor appears on no shard, neither as the [L,...]
    pool nor as a per-layer slice (an all-gather around the manual
    region would reintroduce it)."""
    bundle, params = llama
    cfg = bundle.config
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                      n_pages=9, plan=tp2_plan, shard_kv=True)
    arr = eng.scheduler.decode_arrays()
    hlo = eng._decode_fn.lower(
        eng.params, eng.pages["k"], eng.pages["v"],
        jnp.asarray(arr["tokens"]), jnp.asarray(arr["lengths"]),
        jnp.asarray(arr["tables"]), jnp.asarray(arr["seeds"]),
        jnp.asarray(arr["temps"]), jnp.asarray(arr["top_ks"]),
        jnp.asarray(arr["top_ps"]), jnp.asarray(arr["actives"])
    ).compile().as_text()
    kvh, hd = cfg.num_kv_heads, cfg.head_size
    local = (cfg.num_layers, 9, 4, kvh // 2, hd)
    assert hlo_util.has_aval(hlo, "f32", local), \
        "per-chip (kvh/tp) pool slice missing from the compiled decode"
    for full in ((cfg.num_layers, 9, 4, kvh, hd), (9, 4, kvh, hd)):
        assert not hlo_util.has_aval(hlo, "f32", full), \
            f"full-kv-head pool tensor f32{list(full)} on a shard"
    # and the device arrays themselves are per-chip: each chip's resident
    # share of the pool is 1/2 of the global bytes
    shard_bytes = [
        np.prod(s.data.shape) * 4
        for s in eng.pages["k"].addressable_shards]
    assert all(b == eng.pages["k"].nbytes // 2 for b in shard_bytes)
