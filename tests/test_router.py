"""Fleet router (serve/router.py): prefix-affinity key properties
(page-aligned proper prefix, stable across prefill mode / kv dtype,
random-fleet property test), load-aware + rendezvous routing, 429
spillover honoring retry_after_s, heartbeat fencing with bitwise
resubmission replay, the structured resubmit-exhausted give-up, drain,
readiness gates, and the HTTP layer's Retry-After / /readyz / graceful
drain. Env-knob chaos drills live in test_chaos_serve.py.
"""
import dataclasses
import json
from http.client import HTTPConnection

import jax
import jax.numpy as jnp
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.serve import (RefusalError, Request,
                                                  ServeEngine)
from distributed_training_guide_tpu.serve.api import generate_many, serve_http
from distributed_training_guide_tpu.serve.router import (
    Replica, Router, local_fleet, prefix_affinity_key, readiness,
    rendezvous_order, replica_load)

pytestmark = [pytest.mark.serve, pytest.mark.router]


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


def _fresh(req):
    return dataclasses.replace(req, request_id=None)


def _ref(bundle, params, req, **kw):
    eng = ServeEngine(bundle, params, n_slots=1, prefix_cache=False, **kw)
    return generate_many(eng, [_fresh(req)])[0]


# ---- affinity key properties ------------------------------------------------

def test_affinity_key_is_page_aligned_proper_prefix():
    page = 4
    # no full cacheable page -> no key (<= page tokens: the "proper
    # prefix" rule leaves the last token out, exactly PrefixCache.match)
    assert prefix_affinity_key([1, 2, 3], page) is None
    assert prefix_affinity_key([1, 2, 3, 4], page) is None
    key5 = prefix_affinity_key([1, 2, 3, 4, 5], page)
    assert key5 is not None
    # the tail past the aligned prefix does not move the key...
    assert prefix_affinity_key([1, 2, 3, 4, 99], page) == key5
    assert prefix_affinity_key([1, 2, 3, 4, 5, 6, 7, 8], page) == key5
    # ...but one more full page does, and a different prefix does
    assert prefix_affinity_key([1, 2, 3, 4, 5, 6, 7, 8, 9], page) != key5
    assert prefix_affinity_key([9, 2, 3, 4, 5], page) != key5


def test_affinity_key_sees_only_prompt_and_page_size():
    """The stability satellite, at the source: the key is a pure
    function of (prompt, page_size, adapter) — engine config (chunked vs
    bucket prefill, int8 kv_dtype) cannot appear in it because it is
    never an input. Content-hashed, so stable across processes too."""
    import inspect

    sig = inspect.signature(prefix_affinity_key)
    assert list(sig.parameters) == ["prompt_ids", "page_size",
                                    "adapter_id"]
    assert sig.parameters["adapter_id"].default == 0
    # content hash, not Python hash(): a known digest pins cross-process
    # stability (PYTHONHASHSEED cannot move this)
    assert prefix_affinity_key(list(range(8)), 4).hex() == \
        prefix_affinity_key(tuple(range(8)), 4).hex()
    # adapter 0 keys are bitwise the pre-multi-LoRA keys (base traffic
    # keeps its affinity assignments across an upgrade); tenants fork
    # the keyspace because cached pages are namespaced per adapter slot
    assert prefix_affinity_key(list(range(8)), 4, adapter_id=0) == \
        prefix_affinity_key(list(range(8)), 4)
    assert prefix_affinity_key(list(range(8)), 4, adapter_id=1) != \
        prefix_affinity_key(list(range(8)), 4)
    assert prefix_affinity_key(list(range(8)), 4, adapter_id=1) != \
        prefix_affinity_key(list(range(8)), 4, adapter_id=2)


def test_rendezvous_fencing_moves_only_the_fenced_keys():
    names = ["r0", "r1", "r2", "r3"]
    keys = [prefix_affinity_key(list(range(i, i + 8)), 4)
            for i in range(50)]
    before = {k: rendezvous_order(k, names)[0] for k in keys}
    survivors = [n for n in names if n != "r1"]
    for k in keys:
        after = rendezvous_order(k, survivors)[0]
        if before[k] != "r1":
            assert after == before[k], "non-fenced keys must not move"


# ---- routing over fake engines (pure logic, no compiles) --------------------

class FakeEngine:
    def __init__(self, page_size=4, n_slots=4, queued=0, refuse=None):
        self.page_size, self.n_slots = page_size, n_slots
        self.queued, self.refuse = queued, refuse
        self.decode_steps = self.decode_tokens = 0
        self.submitted, self.resubmitted = [], []
        self.draining = False
        self._ids = iter(range(10 ** 6))

    def stats(self):
        return {"n_slots": self.n_slots, "queued": self.queued,
                "active_slots": 0, "pool_occupancy": 0.0,
                "pages_capacity": 10, "pages_free": 10, "pages_held": 0,
                "draining": self.draining}

    def submit(self, request):
        if self.refuse is not None:
            raise self.refuse
        self.submitted.append(request)
        return next(self._ids)

    def resubmit(self, request, generated=(), first_token_at=0.0,
                 submitted_at=None):
        self.resubmitted.append((request, list(generated), submitted_at))
        return next(self._ids)

    def partial_tokens(self):
        return {}

    def step(self):
        return []

    @property
    def has_work(self):
        return False

    def drain(self):
        self.draining = True


def _fake_fleet(n=3, clock=None, **router_kw):
    replicas = [Replica(f"r{i}", FakeEngine(),
                        clock=clock or (lambda: 0.0)) for i in range(n)]
    return Router(replicas, clock=clock or (lambda: 0.0), **router_kw)


def test_affinity_routes_shared_prefix_to_one_replica():
    router = _fake_fleet(3)
    prefix = list(range(8))
    targets = set()
    for i in range(6):
        rid = router.submit(Request(prompt_ids=prefix + [50 + i]))
        targets.add(router._records[rid].replica)
    assert len(targets) == 1
    assert router.counters["affinity_routed"] == 6


def test_keyless_traffic_routes_least_loaded():
    clock = lambda: 0.0  # noqa: E731
    replicas = [Replica("busy", FakeEngine(queued=5), clock=clock),
                Replica("idle", FakeEngine(queued=0), clock=clock)]
    router = Router(replicas, clock=clock)
    for i in range(4):
        rid = router.submit(Request(prompt_ids=[i, i + 1]))  # no key
        assert router._records[rid].replica == "idle"
    assert router.counters["affinity_routed"] == 0


def test_affinity_miss_on_fenced_target_degrades_cleanly():
    """Fencing the affinity winner reroutes its keys; everyone else's
    stay put (rendezvous), and keyless traffic never sees the fence."""
    router = _fake_fleet(3)
    prefix = list(range(8))
    rid = router.submit(Request(prompt_ids=prefix + [1]))
    winner = router._records[rid].replica
    router.replicas[winner].state = "fenced"
    rid2 = router.submit(Request(prompt_ids=prefix + [2]))
    assert router._records[rid2].replica != winner
    assert router._records[rid2].replica in router.replicas


def test_spillover_on_429_respects_retry_after():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    refusal = RefusalError("queue_full", "full", http_status=429,
                           detail={"queue_depth": 9, "retry_after_s": 1.5})
    replicas = [Replica("full", FakeEngine(refuse=refusal), clock=clock),
                Replica("open", FakeEngine(queued=99), clock=clock)]
    router = Router(replicas, clock=clock)
    # "full" is the less-loaded candidate -> tried first -> 429 ->
    # spillover lands on "open" and "full" backs off for retry_after_s
    rid = router.submit(Request(prompt_ids=[1, 2]))
    assert router._records[rid].replica == "open"
    assert router.counters["spillovers"] == 1
    assert router.replicas["full"].unroutable_until == pytest.approx(1.5)
    # inside the backoff window the refusing replica is not even tried
    rid2 = router.submit(Request(prompt_ids=[3, 4]))
    assert router._records[rid2].replica == "open"
    assert router.counters["spillovers"] == 1
    # past the window it becomes routable again
    t[0] = 2.0
    replicas[0].engine.refuse = None
    rid3 = router.submit(Request(prompt_ids=[5, 6]))
    assert router._records[rid3].replica == "full"


def test_all_replicas_refusing_propagates_429_with_hint():
    refusal = RefusalError("queue_full", "full", http_status=429,
                           detail={"queue_depth": 9, "retry_after_s": 0.7})
    clock = lambda: 0.0  # noqa: E731
    replicas = [Replica(f"r{i}", FakeEngine(refuse=refusal), clock=clock)
                for i in range(2)]
    router = Router(replicas, clock=clock)
    with pytest.raises(RefusalError) as exc:
        router.submit(Request(prompt_ids=[1, 2]))
    assert exc.value.http_status == 429
    assert exc.value.retry_after_s == 0.7


def test_no_live_replica_refuses_503():
    router = _fake_fleet(2)
    for replica in router.replicas.values():
        replica.kill()
    with pytest.raises(RefusalError, match="no live") as exc:
        router.submit(Request(prompt_ids=[1, 2]))
    assert exc.value.http_status == 503


def test_draining_replica_is_unroutable():
    router = _fake_fleet(2)
    prefix = list(range(8))
    rid = router.submit(Request(prompt_ids=prefix + [1]))
    winner = router._records[rid].replica
    router.replicas[winner].drain()
    rid2 = router.submit(Request(prompt_ids=prefix + [2]))
    assert router._records[rid2].replica != winner
    assert router.stats()["replicas"][winner]["draining"]


def test_property_random_fleets_route_live_and_deterministically():
    """Property test over random fleets: every routed request lands on a
    live, non-draining replica; keyed requests land on the rendezvous
    winner among live replicas; the same (fleet state, prompt) always
    routes identically."""
    import random

    rng = random.Random(7)
    for trial in range(30):
        n = rng.randint(1, 5)
        clock = lambda: 0.0  # noqa: E731
        replicas = [Replica(f"r{i}", FakeEngine(queued=rng.randint(0, 5)),
                            clock=clock) for i in range(n)]
        router = Router(replicas, clock=clock)
        fenced = [r for r in replicas if rng.random() < 0.3 and n > 1]
        for r in fenced[:n - 1]:
            r.state = "fenced"
        live = [r.name for r in replicas if r.state == "live"]
        if not live:
            continue
        for _ in range(5):
            prompt = [rng.randint(0, 99)
                      for _ in range(rng.randint(1, 12))]
            req = Request(prompt_ids=prompt)
            try:
                rid = router.submit(req)
            except RefusalError:
                assert not live
                continue
            chosen = router._records[rid].replica
            assert chosen in live
            key = prefix_affinity_key(prompt, 4)
            if key is not None:
                assert chosen == rendezvous_order(key, live)[0]
            else:
                loads = {name: replica_load(
                    router.replicas[name].engine.stats())
                    for name in live}
                assert loads[chosen] == min(loads.values())
            # determinism: the identical submit routes identically
            rid2 = router.submit(dataclasses.replace(req, request_id=None))
            assert router._records[rid2].replica == chosen


def test_wedge_is_fenced_by_heartbeat_age_and_resubmitted():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    router = _fake_fleet(2, clock=clock, heartbeat_timeout_s=1.0)
    rid = router.submit(Request(prompt_ids=list(range(8)) + [1]))
    victim = router._records[rid].replica
    other = next(n for n in router.replicas if n != victim)
    router.replicas[victim].wedge()
    # beats stop; within the timeout nothing fences. Step in increments
    # small enough that the HEALTHY replica keeps beating AND the router
    # is never idle long enough to forgive (gap < timeout/2) — only the
    # wedged one's age crosses the timeout. The FIRST step forgives
    # unconditionally (the pre-traffic window is unobserved), so the
    # wedge clock effectively starts there.
    for tick in (0.4, 0.8, 1.2):
        t[0] = tick
        router.step()
        assert router.replicas[victim].state == "live"
    t[0] = 1.6          # victim's last (forgiven) beat t=0.4 -> age 1.2
    router.step()
    assert router.replicas[victim].state == "fenced"
    assert router.replicas[other].state == "live"
    # the in-flight request moved to the backlog and re-placed on the
    # survivor via resubmit (replay path)
    t[0] = 2.0
    router.step()
    record = router._records[rid]
    assert record.replica == other
    assert router.replicas[other].engine.resubmitted
    assert router.counters["fenced"] == 1
    assert router.counters["resubmitted"] == 1


def test_idle_router_gap_does_not_fence_healthy_fleet():
    """Regression (found driving the real HTTP server): the worker only
    steps a router that has work, so replicas don't beat while the fleet
    is idle — the first request after a quiet spell must NOT find
    everyone fenced. Unobserved windows are forgiven; only staleness
    across DRIVEN steps fences."""
    t = [100.0]         # construction happened "long ago" relative to t=0
    clock = lambda: t[0]  # noqa: E731
    router = _fake_fleet(2, clock=clock, heartbeat_timeout_s=1.0)
    t[0] = 200.0        # a 100s idle gap, 100x the timeout
    rid = router.submit(Request(prompt_ids=[1, 2]))
    router.step()
    assert all(r.state == "live" for r in router.replicas.values())
    assert router._records[rid].replica is not None
    assert router.counters["fenced"] == 0


def test_slow_steps_do_not_mask_a_wedged_replica():
    """The dual of idle-gap forgiveness: time spent INSIDE replica.step
    calls is driven time, not idleness — a fleet whose healthy engine
    steps take longer than heartbeat_timeout/2 must still fence a
    wedged replica (forgiveness keys on the end-of-step -> start-of-step
    gap, never on step duration)."""
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731

    class SlowEngine(FakeEngine):
        @property
        def has_work(self):
            return True

        def step(self):
            t[0] += 1.2         # a slow engine iteration, > timeout/2
            return []

    replicas = [Replica("slow", SlowEngine(), clock=clock),
                Replica("wedged", SlowEngine(), clock=clock)]
    router = Router(replicas, clock=clock, heartbeat_timeout_s=2.0)
    rid = router.submit(Request(prompt_ids=[1, 2]))
    router._records[rid].replica = "wedged"   # pin the victim
    router._by_engine[("wedged", router._records[rid].engine_rid)] = rid
    router.replicas["wedged"].wedge()
    for _ in range(4):          # ages 1.2, 2.4 -> fenced on the 2nd+
        router.step()
    assert router.replicas["wedged"].state == "fenced"
    assert router.replicas["slow"].state == "live"
    assert router.counters["resubmitted"] == 1


def test_resubmit_exhausted_is_a_structured_strict_prefix_result():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    router = _fake_fleet(1, clock=clock)
    rid = router.submit(Request(prompt_ids=[1, 2, 3]))
    router._records[rid].generated = [5, 6]       # tokens the router saw
    router.replicas["r0"].kill()
    t[0] = 1.0
    out = router.step()
    assert [r.request_id for r in out] == [rid]
    assert out[0].finish_reason == "resubmit_exhausted"
    assert out[0].generated_ids == [5, 6]
    assert not router.has_work
    assert router.stats()["resubmit_exhausted"] == 1


def test_resubmission_preserves_original_submit_timestamp():
    """Bugfix pin: a fence/spillover resubmission carries the ORIGINAL
    client submit time through to the engine's requeue — TTFT and
    deadline accounting measure from FIRST submit, not from the hop
    (the scheduler would otherwise restamp its clock and a twice-moved
    request would look forever young to its own deadline)."""
    t = [10.0]
    clock = lambda: t[0]  # noqa: E731
    router = _fake_fleet(2, clock=clock)
    rid = router.submit(Request(prompt_ids=[1, 2, 3]))
    record = router._records[rid]
    assert record.submitted_at == 10.0
    victim = record.replica
    other = next(n for n in router.replicas if n != victim)
    record.generated = [5]              # a token the router already saw
    router.replicas[victim].kill()
    t[0] = 25.0
    router.step()                       # fences victim -> backlog
    t[0] = 25.1                         # past the resubmit backoff
    router.step()                       # re-places on the survivor
    assert router._records[rid].replica == other
    assert router._records[rid].submitted_at == 10.0
    req, gen, submitted_at = router.replicas[other].engine.resubmitted[-1]
    assert gen == [5]
    assert submitted_at == 10.0, \
        "resubmission must thread the original client submit time"


# ---- real-engine identity ---------------------------------------------------

def test_fleet_matches_batch1_and_fence_recovery_replays(llama):
    """End-to-end over real engines: a 2-replica fleet completes a mixed
    workload token-identical to batch-1; killing one replica mid-decode
    fences it and every in-flight request resubmits + replays to the
    SAME tokens (shared params + position-keyed sampling)."""
    bundle, params = llama
    reqs = [Request(prompt_ids=[3 + i, 17, 42, 9, 5][:2 + i % 3],
                    max_new_tokens=8, seed=i,
                    temperature=0.7 if i % 2 else 0.0) for i in range(6)]
    router = local_fleet(bundle, params, 2, n_slots=2, page_size=4,
                         max_len=32,
                         router_kw=dict(heartbeat_timeout_s=60.0))
    ids = [router.submit(_fresh(r)) for r in reqs]
    done, it = {}, 0
    while router.has_work:
        if it == 3:       # mid-decode, no env knob: the direct API
            router.replicas["r0"].kill()
        for res in router.step():
            done[res.request_id] = res
        it += 1
        assert it < 3000
    assert router.stats()["fenced"] == 1
    for rid, req in zip(ids, reqs):
        want = _ref(bundle, params, req, page_size=4, max_len=32)
        assert done[rid].token_ids == want.token_ids, f"seed={req.seed}"
    # survivor audit: pool balanced after the drain
    surv = router.replicas["r1"].engine
    assert surv.scheduler.pool.n_free \
        + surv.scheduler.cache_pages_held() == surv.scheduler.pool.capacity


@pytest.mark.slow
def test_routing_choice_identical_across_engine_configs(llama):
    """The affinity-stability satellite, end to end (the heavy fleet
    grid — 6 engines; the tier-1 pin of the same property is
    test_affinity_key_sees_only_prompt_and_page_size): fleets whose
    replicas differ in prefill mode (bucket vs chunked) and kv dtype
    (fp32 vs int8) route the same prompts to the same replica NAMES —
    the key never sees engine config, so cache locality survives
    heterogeneous rollouts (e.g. an int8 canary)."""
    bundle, params = llama
    prompts = [list(range(1, 9)) + [50 + i] for i in range(3)] \
        + [[9, 8, 7, 6, 5, 4, 3, 2] + [70 + i] for i in range(3)]
    choices = {}
    for tag, kw in (("bucket_fp32", {}),
                    ("chunk_fp32", dict(prefill_chunk=4)),
                    ("bucket_int8", dict(kv_dtype="int8"))):
        router = local_fleet(bundle, params, 2, n_slots=2, page_size=4,
                             max_len=16, **kw)
        routed = []
        for p in prompts:
            rid = router.submit(Request(prompt_ids=list(p),
                                        max_new_tokens=2))
            routed.append(router._records[rid].replica)
        choices[tag] = routed
        while router.has_work:
            router.step()
    assert choices["bucket_fp32"] == choices["chunk_fp32"] \
        == choices["bucket_int8"]


# ---- readiness + HTTP satellites -------------------------------------------

def test_readiness_gates():
    ok = {"ok": True, "draining": False, "n_slots": 4, "max_queue": 8,
          "queued": 0, "pages_free": 10}
    assert readiness(ok) == (True, [])
    assert readiness({**ok, "draining": True})[1] == ["draining"]
    assert readiness({**ok, "queued": 8})[1] == ["queue_depth"]
    assert readiness({**ok, "pages_free": 1})[1] == ["pool_headroom"]
    assert readiness({**ok, "ok": False})[1] == ["engine_dead"]
    assert readiness(ok, loop_age_s=9.0, heartbeat_timeout_s=2.0)[1] \
        == ["heartbeat_stale"]
    ready, reasons = readiness({**ok, "draining": True, "queued": 99})
    assert not ready and set(reasons) == {"draining", "queue_depth"}
    # no max_queue -> the 8x-slots default watermark
    assert readiness({**ok, "max_queue": None, "queued": 32})[1] \
        == ["queue_depth"]


@pytest.mark.stream
def test_http_readyz_retry_after_and_graceful_drain(llama):
    """The HTTP trio: /readyz flips 200 -> 503 (reason 'draining') when
    the engine drains; a post-drain submit gets 503 with a real
    Retry-After header + the float hint in the body; and
    worker.stop(drain=True) completes the in-flight request instead of
    failing it."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16)
    server, worker = serve_http(eng, port=0)
    port = server.server_address[1]
    try:
        conn = HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/readyz")
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["ready"] is True

        # one in-flight request, then drain mid-service
        import threading

        fut = worker.submit(Request(prompt_ids=[3, 17], max_new_tokens=4))
        stopper = threading.Thread(
            target=lambda: worker.stop(drain=True, timeout_s=30.0))
        stopper.start()
        fut["event"].wait(timeout=30)
        assert fut["error"] is None and fut["result"] is not None
        stopper.join(timeout=30)

        conn.request("GET", "/readyz")
        resp = conn.getresponse()
        assert resp.status == 503
        assert "draining" in json.loads(resp.read())["reasons"]

        conn.request("POST", "/generate", body=json.dumps(
            {"prompt_ids": [3, 17], "max_new_tokens": 2}),
            headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 503
        assert resp.getheader("Retry-After") is not None
        assert int(resp.getheader("Retry-After")) >= 1
        body = json.loads(resp.read())
        assert body["reason"] == "draining"
        assert body["retry_after_s"] > 0
        conn.close()
    finally:
        server.shutdown()
        worker.stop()


@pytest.mark.stream
def test_router_serves_http_unchanged(llama):
    """api.py over a FLEET: the router implements the engine surface, so
    POST /generate and /healthz work with zero HTTP-layer changes."""
    bundle, params = llama
    router = local_fleet(bundle, params, 2, n_slots=2, page_size=4,
                         max_len=16)
    server, worker = serve_http(router, port=0)
    port = server.server_address[1]
    try:
        conn = HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/generate", body=json.dumps(
            {"prompt_ids": [3, 17, 42], "max_new_tokens": 4}),
            headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        got = json.loads(resp.read())
        want = _ref(bundle, params,
                    Request(prompt_ids=[3, 17, 42], max_new_tokens=4),
                    page_size=4, max_len=16)
        assert got["token_ids"] == want.token_ids
        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        assert health["router"] is True and health["live_replicas"] == 2
        conn.close()
    finally:
        server.shutdown()
        worker.stop()


def test_mixed_page_size_fleet_rejected(llama):
    bundle, params = llama
    r0 = Replica("r0", FakeEngine(page_size=4))
    r1 = Replica("r1", FakeEngine(page_size=8))
    with pytest.raises(ValueError, match="page_size"):
        Router([r0, r1])
    with pytest.raises(ValueError, match="unique"):
        Router([Replica("x", FakeEngine()), Replica("x", FakeEngine())])
