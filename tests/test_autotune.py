"""Unit tests for the performance-tuning walk's pure pieces (the probe
subprocess itself is exercised by running the script; see
related-topics/performance-tuning/README.md)."""
import importlib.util
import pathlib

spec = importlib.util.spec_from_file_location(
    "autotune",
    pathlib.Path(__file__).parent.parent
    / "related-topics" / "performance-tuning" / "autotune.py")
autotune = importlib.util.module_from_spec(spec)
spec.loader.exec_module(autotune)


def test_parse_step_ms_takes_last_window():
    out = ("INFO:{'global_step': 2, 'time/total': 3400.0, 'mfu': 0.001}\n"
           "INFO:{'global_step': 4, 'time/total': 75.2, 'mfu': 0.5}\n")
    assert autotune.parse_step_ms(out) == 75.2   # post-compile window
    assert autotune.parse_mfu(out) == 0.5
    assert autotune.parse_step_ms("no logs here") is None


def test_classify_failure_matches_bench_markers():
    assert autotune.classify_failure("... Out of memory while ...") == "oom"
    assert autotune.classify_failure("Largest program allocations: ...") == "oom"
    assert autotune.classify_failure("RESOURCE_EXHAUSTED: pool") == "pool_exhausted"
    assert autotune.classify_failure("Traceback ...") == "failed"


def test_plan_walk_order_and_batch_ladder():
    import argparse
    args = argparse.Namespace(batch=8, seq=2048)
    plan = autotune.plan_walk(args)
    names = [s["name"] for s in plan]
    # the README's measured order: fence first, remat ladder, optimizer,
    # the remat RETRY (the headline's attn_mlp only fits after adafactor
    # frees the moments), chunks, batch LAST (every earlier lever moves
    # the HBM knee)
    assert names[:2] == ["baseline", "fence4"]
    assert names[2:5] == ["remat_all", "remat_attn", "remat_attn_mlp"]
    assert names[5] == "adafactor"
    assert names[6:8] == ["remat_attn_after_adafactor",
                          "remat_attn_mlp_after_adafactor"]
    assert names[8] == "loss_chunks8"
    assert names[9:] == ["batch_16", "batch_32"]
    assert all("--fence-every" in s["flags"] for s in plan if s["name"] == "fence4")


def test_compose_flags_remat_retry_keeps_later_levers():
    """The post-adafactor attn_mlp retry must probe attn_mlp WITH adafactor
    — replacing the kept policy segment must preserve levers kept after it."""
    kept = ["--fence-every", "4", "--checkpoint-activations",
            "--remat-policy", "attn", "--optimizer", "adafactor"]
    out = autotune.compose_flags(
        kept, "remat_attn_mlp_after_adafactor",
        ["--checkpoint-activations", "--remat-policy", "attn_mlp"])
    assert out == ["--fence-every", "4", "--optimizer", "adafactor",
                   "--checkpoint-activations", "--remat-policy", "attn_mlp"]
    # non-remat steps simply append
    assert autotune.compose_flags(["--fence-every", "4"], "adafactor",
                                  ["--optimizer", "adafactor"]) == \
        ["--fence-every", "4", "--optimizer", "adafactor"]


def test_probe_cmd_builds_runner_invocation(tmp_path):
    import argparse
    args = argparse.Namespace(model="llama-debug", seq=128, steps=12)
    cmd = autotune.probe_cmd(args, batch=2,
                             flags=["--fence-every", "4"], save_dir=str(tmp_path))
    assert cmd[1].endswith("01-single-chip/train_llm.py")
    assert "--max-steps" in cmd and cmd[cmd.index("--max-steps") + 1] == "12"
    assert cmd[-2:] == ["--fence-every", "4"]
    # the log window must be >= the fence depth the walk recommends —
    # smaller would silently cap --fence-every 4 at the log boundary
    assert cmd[cmd.index("--log-freq") + 1] == "4"
