"""Disaggregated prefill/decode serving (serve/disagg.py): engine-pair
token identity vs batch-1, the zero-copy page handoff (the decode engine
adopts the SAME physical pages the prefill engine committed — refcount
transfer, no device copy), prefill isolation from resident decodes, and
decode-side preemption requeueing through the prefill engine.
"""
import dataclasses

import jax
import jax.numpy as jnp
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.serve import Request, ServeEngine
from distributed_training_guide_tpu.serve.api import generate_many
from distributed_training_guide_tpu.serve.disagg import DisaggEngine

pytestmark = [pytest.mark.serve, pytest.mark.disagg]


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


def _fresh(req):
    return dataclasses.replace(req, request_id=None)


def _ref(bundle, params, req, **kw):
    eng = ServeEngine(bundle, params, n_slots=1, prefix_cache=False, **kw)
    return generate_many(eng, [_fresh(req)])[0]


@pytest.mark.parametrize("chunk", [None, 4])
def test_disagg_matches_batch1(llama, chunk):
    """Bucketed AND chunked prefill engines: every request through the
    pair — co-residency, temperature, eos — equals its batch-1 run, the
    handoff moved pages with zero bytes copied, and the pool balances."""
    bundle, params = llama
    reqs = [Request(prompt_ids=[3 + i, 17, 42][:(i % 3) + 1],
                    max_new_tokens=3 + (i % 4),
                    temperature=0.8 if i % 2 else 0.0, seed=i)
            for i in range(8)]
    eng = DisaggEngine(bundle, params, n_slots=3, n_prefill_slots=2,
                       page_size=4, max_len=16, prefill_chunk=chunk)
    res = generate_many(eng, [_fresh(r) for r in reqs])
    for got, req in zip(res, reqs):
        want = _ref(bundle, params, req, page_size=4, max_len=16)
        assert got.token_ids == want.token_ids
    assert eng.handoff.stats["transfers"] == 8
    assert eng.handoff.stats["bytes_copied"] == 0
    pool = eng.pool
    assert pool.n_free + eng.prefill.sched.cache_pages_held() \
        == pool.capacity


def test_handoff_transfers_ownership_of_the_same_physical_pages(llama):
    """The zero-copy acceptance pin, mechanically: record the physical
    page ids each Handoff carries out of the prefill engine, then catch
    the decode slot READING those very ids — ownership moved, contents
    did not (cow_forks == 0, bytes_copied == 0, and the refcounts
    balance to exactly one holder per page throughout)."""
    bundle, params = llama
    eng = DisaggEngine(bundle, params, n_slots=2, page_size=4, max_len=32,
                       prefill_chunk=4, prefix_cache=False)
    transferred = []
    orig = eng.handoff.transfer
    eng.handoff.transfer = lambda h: (transferred.append(
        (h.request.request_id, list(h.pages))), orig(h))[-1]
    rid = eng.submit(Request(prompt_ids=[9, 8, 7, 6, 5], max_new_tokens=6))
    seen_in_decode = None
    it = 0
    while eng.has_work:
        eng.step()
        for slot in eng.decode.sched.slots:
            if slot is not None and slot.request.request_id == rid:
                seen_in_decode = list(slot.pages)
        it += 1
        assert it < 200
    assert transferred and transferred[0][0] == rid
    # the decode slot may GROW extra pages as it generates; its table
    # must START with exactly the physical ids the prefill committed
    moved = transferred[0][1]
    assert seen_in_decode is not None \
        and seen_in_decode[:len(moved)] == moved, \
        "decode engine must read the pages the prefill engine committed"
    assert eng.handoff.stats["bytes_copied"] == 0
    assert eng.prefill.sched.stats["cow_forks"] == 0
    assert eng.pool.n_free == eng.pool.capacity


def test_prefill_engine_never_stalls_resident_decodes(llama):
    """The DistServe motivation, pinned: while a 60-token prompt streams
    through the PREFILL engine, a resident sequence in the DECODE engine
    keeps producing a token on (almost) every iteration — prefill work
    no longer sits inside the decode program's iteration."""
    bundle, params = llama
    eng = DisaggEngine(bundle, params, n_slots=2, n_prefill_slots=1,
                       page_size=4, max_len=128, prefill_chunk=8)
    short = Request(prompt_ids=[5, 6], max_new_tokens=24, seed=1)
    rid_short = eng.submit(short)
    for _ in range(3):         # admit, hand off, seat in decode
        eng.step()
    long_req = Request(prompt_ids=[3 + (i % 200) for i in range(60)],
                       max_new_tokens=4, seed=2)
    rid_long = eng.submit(long_req)

    results, decode_ticks, prefill_iters = [], 0, 0
    it = 0
    while eng.has_work:
        before = dict(eng.partial_tokens())
        prefilling = any(s is not None and s.prefilling
                         for s in eng.prefill.sched.slots)
        results.extend(eng.step())
        after = dict(eng.partial_tokens())
        if prefilling:
            prefill_iters += 1
            if len(after.get(rid_short, [])) \
                    > len(before.get(rid_short, [])):
                decode_ticks += 1
        it += 1
        assert it < 500
    # the 60-token prompt spans >= 7 chunk iterations after admission;
    # the resident decode advanced through essentially all of them
    assert prefill_iters >= 7
    assert decode_ticks >= prefill_iters - 1

    by_id = {r.request_id: r for r in results}
    for rid, req in ((rid_short, short), (rid_long, long_req)):
        want = _ref(bundle, params, req, page_size=4, max_len=128)
        assert by_id[rid].token_ids == want.token_ids


def test_decode_preemption_requeues_through_prefill_engine(llama):
    """Decode-side exhaustion preempts; the entry routes BACK to the
    prefill queue (only it can recompute a prompt), re-prefills,
    re-hands-off, and REPLAYS its recorded tokens — every completion
    still byte-identical to batch-1, pool balanced, pressure visible."""
    bundle, params = llama
    # admission is headroom-guarded (one page per running decode), so
    # pressure must come from GROWTH: short prompts admit cheaply into
    # one page each, then every sequence generates to ~4 pages — 4
    # co-residents want 16 of the 9 usable pages mid-flight
    eng = DisaggEngine(bundle, params, n_slots=4, n_prefill_slots=1,
                       page_size=4, max_len=16, n_pages=10,
                       prefill_chunk=4)
    reqs = [Request(prompt_ids=[3 + i, 17],
                    max_new_tokens=12 + (i % 2),
                    temperature=0.7 if i % 2 else 0.0, seed=i)
            for i in range(8)]
    res = generate_many(eng, [_fresh(r) for r in reqs],
                        max_iterations=3000)
    stats = eng.stats()
    assert stats["preempted"] > 0, "the trace never hit real pressure"
    for got, req in zip(res, reqs):
        want = _ref(bundle, params, req, page_size=4, max_len=16)
        assert got.token_ids == want.token_ids, \
            f"seed={req.seed} diverged across preempt+rehandoff"
    pool = eng.pool
    assert pool.n_free + eng.prefill.sched.cache_pages_held() \
        == pool.capacity


def test_disagg_composes_with_sharded_pool(llama, eight_devices):
    """The full plane: disaggregated pair over the kv-head-sharded pool
    (the handoff moves page ids — shard-agnostic). Token identity vs the
    plain single-device monolith."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    bundle, params = llama
    plan = make_plan("tp", make_mesh(tp=2, devices=eight_devices[:2]))
    reqs = [Request(prompt_ids=[3, 17, 42], max_new_tokens=5, seed=1),
            Request(prompt_ids=[5, 6], max_new_tokens=4, seed=2,
                    temperature=0.9)]
    pair = generate_many(
        DisaggEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                     prefill_chunk=4, plan=plan, shard_kv=True),
        [_fresh(r) for r in reqs])
    mono = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16),
        [_fresh(r) for r in reqs])
    for a, b in zip(pair, mono):
        assert a.token_ids == b.token_ids


def test_disagg_stats_and_kv_report_surface(llama):
    """The facade's metrics snapshot: handoff counters, both engines'
    occupancy, and the kv report — all host-side (no device sync)."""
    bundle, params = llama
    eng = DisaggEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                       prefill_chunk=4)
    generate_many(eng, [Request(prompt_ids=[3, 17], max_new_tokens=4)])
    s = eng.stats()
    assert s["handoff_transfers"] == 1
    assert s["handoff_bytes_copied"] == 0
    assert s["finished"] == 1 and s["decode_steps"] > 0
    assert 0 < s["decode_occupancy"] <= 1.0
    assert s["ttft_s_avg"] > 0
    rep = eng.kv_report()
    assert rep["kv_shards"] == 1
    assert rep["bytes_per_page"] == rep["bytes_per_page_per_chip"]
