"""Latency-hiding schedule coverage (ops/overlap.py, --overlap-schedule).

Two pins per the acceptance criteria:

- *numerical parity*: the scheduled program (unrolled layers, manual
  per-layer fsdp all-gather / grad reduce-scatter, ring EP exchange, fused
  hidden->loss kernel) tracks the unscheduled GSPMD program's loss
  trajectory to <= 1e-5 RELATIVE over >= 3 optimizer steps. The programs
  are mathematically identical; differences are reassociation-level fp
  noise (different chunk/block grouping, Adam-amplified across steps),
  which rtol=1e-5 (~6e-5 absolute at loss 6.3, observed diffs <= 3e-5)
  bounds.
- *schedule structure in HLO*: the scheduled step carries its collectives
  as per-layer per-direction ops in the FLAT program (count scales 2*L*
  n_gathered; a lax.scan reuses one per leaf inside the loop), the fused
  loss never materializes full-logits fp32 tensors, and — on backends that
  emit them (TPU with the latency-hiding scheduler) — async collective
  start/done pairs span compute. CPU lowers collectives synchronously, so
  the async-pair assertion engages conditionally; the pair-parser itself is
  unit-tested on synthetic HLO below.

Multi-device parity grids beyond the core fsdp/ep/fused cases need >2
virtual devices' worth of compile time and are marked ``slow`` (tier-1
runs ``-m 'not slow'`` inside an 870s budget).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine
from distributed_training_guide_tpu.utils import hlo as hlo_util

pytestmark = pytest.mark.overlap

STEPS = 3
RTOL = 1e-5
ATOL = 1e-7  # losses are O(6); rtol dominates


def _trainer(bundle, plan, overlap, **kw):
    return Trainer(bundle=bundle, optimizer=adamw_cosine(3e-5), plan=plan,
                   attn_impl="xla", overlap_schedule=overlap, donate=False,
                   **kw)


def _losses(trainer, vocab, steps=STEPS, batch=8, seq=32, grad_accum=1):
    state = trainer.init_state(0)
    rng = np.random.RandomState(0)
    out = []
    for _ in range(steps):
        ids = rng.randint(0, vocab, (batch, seq))
        arr = jnp.asarray(ids)
        if grad_accum > 1:
            arr = arr.reshape(grad_accum, batch // grad_accum, seq)
        b = {k: jax.device_put(arr, trainer.batch_shardings()[k])
             for k in ("input_ids", "labels")}
        state, m = trainer.step_fn(state, b)
        out.append(float(m["loss"]))
    return np.asarray(out)


def _assert_parity(bundle, plan, **kw):
    a = _losses(_trainer(bundle, plan, False, **kw), bundle.config.vocab_size,
                grad_accum=kw.get("grad_accum", 1))
    b = _losses(_trainer(bundle, plan, True, **kw), bundle.config.vocab_size,
                grad_accum=kw.get("grad_accum", 1))
    np.testing.assert_allclose(b, a, rtol=RTOL, atol=ATOL)


# ---------------------------------------------------------------------------
# core parity + HLO pin (tier-1): one 2-device fsdp case carries both — the
# wider grids (4-device fsdp/ep, precision, composite meshes) are slow
# ---------------------------------------------------------------------------

def test_fsdp_overlap_parity_and_hlo_pin(eight_devices):
    """The acceptance core on a 2-device fsdp mesh: (a) the scheduled
    program (per-layer gather/reduce-scatter + fused loss) tracks GSPMD to
    rtol 1e-5 over 3 steps; (b) its compiled HLO carries one all-gather per
    gathered leaf per layer per direction in the FLAT program — 2 * L * 7
    for llama-debug (wq wk wv wo gate up down; fwd + backward re-gather) —
    strictly more distinct collectives than the unscheduled scan, none
    under a while body, plus per-layer reduce-scatters; (c) the fused loss
    lowers with NO full-logits fp32 tensor at any shard size; (d) on
    backends whose scheduler emits async start/done pairs (TPU
    latency-hiding scheduler), the pairs span compute — CPU lowers
    collectives synchronously, so that clause engages conditionally (the
    parser itself is unit-tested on synthetic HLO below)."""
    bundle = get_model("llama-debug")
    plan = make_plan("fsdp", make_mesh(fsdp=2, devices=eight_devices[:2]))
    kw = dict(remat=True, remat_policy="attn", loss_chunks=4)
    t_uns = _trainer(bundle, plan, False, **kw)
    t_sch = _trainer(bundle, plan, True, **kw)
    a = _losses(t_uns, bundle.config.vocab_size)
    b = _losses(t_sch, bundle.config.vocab_size)
    np.testing.assert_allclose(b, a, rtol=RTOL, atol=ATOL)

    sch = _compiled_step_text(t_sch)
    uns = _compiled_step_text(t_uns)
    L, n_gathered = bundle.config.num_layers, 7
    free = hlo_util.collectives_outside_loops(sch, kinds=("all-gather",))
    assert len(free) >= 2 * L * n_gathered, \
        f"expected >= {2 * L * n_gathered} flat all-gathers, got {len(free)}"
    in_loop = [c for c in hlo_util.find_collectives(sch, ("all-gather",))
               if c.computation in hlo_util.while_body_computations(sch)]
    assert not in_loop, "scheduled gathers must not sit inside a loop body"
    assert len(free) > len(hlo_util.find_collectives(uns, ("all-gather",))), \
        "schedule must unroll to MORE distinct collectives than the scan"
    assert hlo_util.find_collectives(sch, kinds=("reduce-scatter",)), \
        "per-layer grad reduce-scatter missing"

    # fused loss: no [B, S-1, V] / flattened fp32 logits, global or local
    v = bundle.config.vocab_size
    for rows in (8 * 31, 4 * 31):              # global / per-fsdp-member
        assert not hlo_util.has_aval(sch, "f32", (rows, v))
    for b_ in (8, 4):
        assert not hlo_util.has_aval(sch, "f32", (b_, 31, v))

    pairs = hlo_util.async_collective_pairs(sch)
    if pairs:  # TPU latency-hiding scheduler; CPU lowers sync
        hlo_util.assert_async_pairs_span_compute(sch)


@pytest.mark.slow
def test_fsdp4_overlap_parity(eight_devices):
    """The 4-way fsdp mesh (the acceptance shape beyond tier-1's 2-way)."""
    bundle = get_model("llama-debug")
    plan = make_plan("fsdp", make_mesh(fsdp=4, devices=eight_devices[:4]))
    _assert_parity(bundle, plan, remat=True, remat_policy="attn",
                   loss_chunks=4)


@pytest.mark.slow
def test_ep_ring_overlap_parity(eight_devices):
    """Ragged MoE under ep: the double-buffered ppermute ring computes the
    same dispatch as the bulk all-gather + reduce-scatter exchange."""
    bundle = get_model("moe-debug", moe_dispatch="ragged")
    plan = make_plan("ep", make_mesh(ep=4, devices=eight_devices[:4]))
    _assert_parity(bundle, plan)


@pytest.mark.slow
def test_zero1_overlap_parity(eight_devices):
    """zero1 (params replicated, opt state sharded): the schedule reduces
    to the flat unrolled program with zero gathers — still parity."""
    bundle = get_model("llama-debug")
    plan = make_plan("zero1", make_mesh(fsdp=2, devices=eight_devices[:2]))
    _assert_parity(bundle, plan)


def test_fused_loss_matches_reference_exactly():
    """Single-shard fused hidden->loss kernel: value AND both gradients are
    bit-identical to the straight [B,S,V] reference (same matmul shapes,
    fp32 chunk math, fp32 dw accumulation)."""
    from distributed_training_guide_tpu.ops.cross_entropy import (
        causal_lm_loss, fused_linear_cross_entropy)

    rng = np.random.RandomState(0)
    b, s, e, v = 2, 17, 8, 37
    h = jnp.asarray(rng.randn(b, s, e), jnp.bfloat16)
    w = jnp.asarray(rng.randn(e, v) * 0.1, jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, v, (b, s)), jnp.int32)
    labels = labels.at[0, 3].set(-100)   # ignored position

    def ref(h, w):
        logits = jnp.einsum("bse,ev->bsv", h, w,
                            preferred_element_type=jnp.float32)
        return causal_lm_loss(logits, labels)

    def fused(h, w):
        nll, cnt = fused_linear_cross_entropy(h, w, labels, num_chunks=4)
        return nll / jnp.maximum(cnt, 1.0)

    vr, (ghr, gwr) = jax.value_and_grad(ref, argnums=(0, 1))(h, w)
    vf, (ghf, gwf) = jax.value_and_grad(fused, argnums=(0, 1))(h, w)
    assert float(vr) == float(vf)
    np.testing.assert_array_equal(np.asarray(ghr, np.float32),
                                  np.asarray(ghf, np.float32))
    np.testing.assert_array_equal(np.asarray(gwr, np.float32),
                                  np.asarray(gwf, np.float32))


def test_fused_loss_sharded_grads_match_reference(eight_devices):
    """GRAD-LEVEL pin of make_fused_loss across vocab shardings — the
    trajectory parity tests CANNOT catch a uniform gradient scale (Adam
    updates are invariant to it), and exactly that bug existed: under tp
    the region's replicated-scalar output splits its cotangent 1/tp across
    the manual axis, which the dh path recompensates through its exit
    collectives but the dw path did not — lm_head grads came back tp-times
    too small until the kernel's backward psum'd the incoming scalar
    cotangent for dw (ops/cross_entropy.py). Pin values AND both grads
    against the dense [B,S,V] reference: tp must be exact (fp32 math end to
    end on the w path), fsdp's reduce-scattered dw is bf16-rounded once."""
    from distributed_training_guide_tpu.ops.cross_entropy import (
        causal_lm_loss)
    from distributed_training_guide_tpu.ops.overlap import make_fused_loss

    rng = np.random.RandomState(0)
    b, s, e, v = 4, 16, 8, 32
    h = jnp.asarray(rng.randn(b, s, e), jnp.bfloat16)
    w = jnp.asarray(rng.randn(e, v) * 0.1, jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, v, (b, s)), jnp.int32)

    def ref(h, w):
        logits = jnp.einsum("bse,ev->bsv", h, w,
                            preferred_element_type=jnp.float32)
        return causal_lm_loss(logits, labels)

    vr, (ghr, gwr) = jax.jit(jax.value_and_grad(ref, argnums=(0, 1)))(h, w)
    for strategy, mesh_kw in (("tp", dict(tp=2)), ("fsdp", dict(fsdp=2))):
        plan = make_plan(strategy, make_mesh(devices=eight_devices[:2],
                                             **mesh_kw))
        fused = make_fused_loss(plan, num_chunks=4)
        vf, (ghf, gwf) = jax.jit(jax.value_and_grad(
            lambda h, w: fused(h, w, labels), argnums=(0, 1)))(h, w)
        assert float(vr) == pytest.approx(float(vf), rel=1e-6), strategy
        np.testing.assert_allclose(np.asarray(ghf, np.float32),
                                   np.asarray(ghr, np.float32),
                                   rtol=1e-5, atol=1e-6, err_msg=strategy)
        # the scale pin: a 1/axis (or x axis) systematic factor on dw is
        # the regression this test exists for
        num = float(jnp.sum(gwf.astype(jnp.float32)
                            * gwr.astype(jnp.float32)))
        den = float(jnp.sum(gwr.astype(jnp.float32) ** 2))
        assert num / den == pytest.approx(1.0, abs=1e-3), strategy
        np.testing.assert_allclose(np.asarray(gwf, np.float32),
                                   np.asarray(gwr, np.float32),
                                   rtol=5e-3, atol=5e-4, err_msg=strategy)


# ---------------------------------------------------------------------------
# further HLO pins
# ---------------------------------------------------------------------------

def _compiled_step_text(trainer, batch=8, seq=32):
    from distributed_training_guide_tpu.checkpoint import abstract_train_state

    state = abstract_train_state(trainer)
    b = {k: jax.ShapeDtypeStruct((batch, seq), np.int32, sharding=sh)
         for k, sh in trainer.batch_shardings().items()}
    return trainer.step_fn.lower(state, b).compile().as_text()


@pytest.mark.slow
def test_ep_ring_hlo_uses_collective_permute(eight_devices):
    """The ring exchange lowers to collective-permutes (the double-buffered
    hops) where the bulk form used all-gather + reduce-scatter."""
    bundle = get_model("moe-debug", moe_dispatch="ragged")
    plan = make_plan("ep", make_mesh(ep=4, devices=eight_devices[:4]))
    sch = _compiled_step_text(_trainer(bundle, plan, True))
    uns = _compiled_step_text(_trainer(bundle, plan, False))
    n_sch = len(hlo_util.find_collectives(sch, ("collective-permute",)))
    n_uns = len(hlo_util.find_collectives(uns, ("collective-permute",)))
    assert n_sch > n_uns, (n_sch, n_uns)
    # each MoE layer's ring: (ep-1) forward hops x 3 operands + (ep-1)
    # return hops, before backward transposes
    assert n_sch >= 4 * (4 - 1)


# ---------------------------------------------------------------------------
# utils/hlo.py parser units (no device work)
# ---------------------------------------------------------------------------

_SYNTH = """\
HloModule synth

%loop_body (p: (s32[], f32[8])) -> (s32[], f32[8]) {
  %ag.9 = f32[32] all-gather(f32[8] %x9), dimensions={0}
  ROOT %t = (s32[], f32[8]) tuple(%i, %y)
}

%cond (p: (s32[], f32[8])) -> pred[] {
  ROOT %lt = pred[] compare(%i, %n), direction=LT
}

ENTRY %main (a: f32[16,8]) -> f32[] {
  %ag-start.1 = (f32[16,8]{1,0}, f32[64,8]{1,0}) all-gather-start(f32[16,8] %a), dimensions={0}
  %fusion.1 = f32[16,8] fusion(f32[16,8] %a), kind=kLoop, calls=%fc
  %ag-done.1 = f32[64,8]{1,0} all-gather-done((f32[16,8], f32[64,8]) %ag-start.1)
  %w = (s32[], f32[8]) while((s32[], f32[8]) %init), condition=%cond, body=%loop_body
  %rs.2 = f32[4,8] reduce-scatter(f32[16,8] %fusion.1), dimensions={0}
  ROOT %r = f32[] constant(0)
}
"""


def test_hlo_parser_units():
    cols = hlo_util.find_collectives(_SYNTH)
    kinds = sorted(c.kind for c in cols)
    assert kinds == ["all-gather", "all-gather", "all-gather",
                     "reduce-scatter"]
    assert hlo_util.while_body_computations(_SYNTH) >= {"%loop_body",
                                                        "%cond"}
    free = hlo_util.collectives_outside_loops(_SYNTH, ("all-gather",))
    assert {c.name for c in free} == {"%ag-start.1", "%ag-done.1"}

    pairs = hlo_util.async_collective_pairs(_SYNTH)
    assert len(pairs) == 1 and pairs[0][0].name == "%ag-start.1"
    # the fusion between start and done counts as spanned compute
    assert hlo_util.assert_async_pairs_span_compute(_SYNTH) == 1

    assert hlo_util.has_aval(_SYNTH, "f32", (16, 8))
    assert hlo_util.has_aval("tensor<16x8xf32>", "f32", (16, 8))
    assert not hlo_util.has_aval(_SYNTH, "f32", (16, 9))
    assert hlo_util.has_shape_run("tensor<4x16x8xbf16>", (16, 8))
    assert not hlo_util.has_shape_run("tensor<116x8xbf16>", (16, 8))


def test_async_pair_assert_fails_without_pairs():
    with pytest.raises(AssertionError):
        hlo_util.assert_async_pairs_span_compute("ENTRY %m (a: f32[2]) -> "
                                                 "f32[2] {\n}\n")


# ---------------------------------------------------------------------------
# validation: illegal combinations fail loudly
# ---------------------------------------------------------------------------

def test_overlap_rejected_under_pp(eight_devices):
    bundle = get_model("llama-debug")
    plan = make_plan("pp", make_mesh(pp=2, devices=eight_devices[:2]))
    with pytest.raises(ValueError, match="pipeline"):
        _trainer(bundle, plan, True)


def test_overlap_rejected_under_cp(eight_devices):
    bundle = get_model("llama-debug")
    plan = make_plan("ddp", make_mesh(cp=2, devices=eight_devices[:2]))
    with pytest.raises(ValueError, match="context parallelism"):
        _trainer(bundle, plan, True)


def test_overlap_rejected_for_lora(eight_devices):
    from distributed_training_guide_tpu.models.lora import lora_bundle

    bundle = lora_bundle(get_model("llama-debug"), rank=2)
    plan = make_plan("fsdp", make_mesh(fsdp=2, devices=eight_devices[:2]))
    t = _trainer(bundle, plan, True)
    with pytest.raises(ValueError, match="layers"):
        t.step_fn  # noqa: B018  (build-time validation)


def test_fused_loss_skipped_for_final_softcap(eight_devices):
    """Gemma-2's final_logit_softcap lives in lm_head_logits, which the
    fused kernel bypasses — the Trainer must fall back to the standard
    loss, not silently drop the cap."""
    from distributed_training_guide_tpu.models.registry import family_module
    from distributed_training_guide_tpu.ops.cross_entropy import (
        causal_lm_loss)
    from distributed_training_guide_tpu.ops.overlap import (
        fused_loss_supported)

    bundle = get_model("llama-debug", final_logit_softcap=30.0)
    plan = make_plan("fsdp", make_mesh(fsdp=2, devices=eight_devices[:2]))
    reason = fused_loss_supported(plan, bundle.config,
                                  family_module("llama"), causal_lm_loss)
    assert reason is not None and "softcap" in reason
    # the trainer still builds and runs (standard loss path)
    t = _trainer(bundle, plan, True)
    assert t.step_fn is not None


# ---------------------------------------------------------------------------
# extended parity grids — need >2 virtual devices of compile budget: slow
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fsdp_bf16_master_overlap_parity(eight_devices):
    """fsdp x precision policy: bf16 param storage gathers/reduces through
    the schedule's collectives (the guarded sub-fp32 path off-TPU)."""
    bundle = get_model("llama-debug")
    plan = make_plan("fsdp", make_mesh(fsdp=4, devices=eight_devices[:4]))
    _assert_parity(bundle, plan, precision="bf16-master")


@pytest.mark.slow
def test_ep_fsdp_overlap_parity(eight_devices):
    """ep x fsdp: ring exchange + manual embed-dim FSDP inside the EP
    region + layer-schedule gathers for the attention weights."""
    bundle = get_model("moe-debug", moe_dispatch="ragged")
    plan = make_plan("ep_fsdp", make_mesh(ep=2, fsdp=2,
                                          devices=eight_devices[:4]))
    _assert_parity(bundle, plan)


@pytest.mark.slow
def test_tp_fused_vocab_parallel_loss_parity(eight_devices):
    """tp plan: the fused kernel runs the vocab-parallel logsumexp/pick
    with explicit tp psums + the SP sequence gather."""
    bundle = get_model("llama-debug")
    plan = make_plan("tp", make_mesh(tp=4, devices=eight_devices[:4]))
    _assert_parity(bundle, plan, loss_chunks=4)


@pytest.mark.slow
def test_tp_fsdp_composite_overlap_parity(eight_devices):
    """dp x tp x fsdp: gathers carry the tp shard through the region
    (in/out specs keep it), the transpose psums the dp contribution."""
    bundle = get_model("llama-debug")
    plan = make_plan("tp_fsdp", make_mesh(dp=2, tp=2, fsdp=2))
    _assert_parity(bundle, plan)


@pytest.mark.slow
def test_zero2_grad_accum_overlap_parity(eight_devices):
    """zero2 + grad accumulation: the sharded accum buffer composes with
    the schedule's per-layer reduce-scatters."""
    bundle = get_model("llama-debug")
    plan = make_plan("zero2", make_mesh(fsdp=4, devices=eight_devices[:4]))
    _assert_parity(bundle, plan, grad_accum=2)


@pytest.mark.slow
@pytest.mark.parametrize("name", ["gpt2-debug", "neox-debug"])
def test_other_families_overlap_parity(eight_devices, name):
    """gpt2/neox take the layer_schedule too (no window column)."""
    bundle = get_model(name)
    plan = make_plan("fsdp", make_mesh(fsdp=2, devices=eight_devices[:2]))
    _assert_parity(bundle, plan)
