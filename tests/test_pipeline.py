"""Pipeline-parallel parity tests on the virtual 8-device mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine

GB = 8
SEQ = 32


def run(strategy, mesh_kw, pp_microbatches=None, steps=2, n_devices=None,
        bundle=None, **trainer_kw):
    bundle = bundle or get_model("llama-debug", dtype=jnp.float32)
    if strategy == "single":
        mesh = make_mesh(devices=jax.devices()[:1])
    else:
        devices = jax.devices()[:n_devices] if n_devices else None
        mesh = make_mesh(devices=devices, **mesh_kw)
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan(strategy, mesh), donate=False,
                pp_microbatches=pp_microbatches, **trainer_kw)
    state = t.init_state(0)
    ids = np.random.RandomState(0).randint(0, 512, (GB, SEQ))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    losses = []
    for _ in range(steps):
        state, m = t.step_fn(state, batch)
        losses.append(float(m["loss"]))
    return losses, state


@pytest.fixture(scope="module")
def golden():
    return run("single", {})


def test_pp_matches_single(golden, eight_devices):
    # llama-debug has 2 layers -> pp=2 stages of 1 layer; dp=4 so the
    # microbatch (GB/M = 4) must stay divisible by dp
    losses, state = run("pp", {"pp": 2}, pp_microbatches=2)
    np.testing.assert_allclose(losses, golden[0], rtol=2e-4)
    for a, b in zip(jax.tree.leaves(jax.device_get(golden[1].params)),
                    jax.tree.leaves(jax.device_get(state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-2, atol=1e-4)


def test_pp_params_sharded(eight_devices):
    bundle = get_model("llama-debug", dtype=jnp.float32)
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("pp", make_mesh(pp=2)), donate=False)
    state = t.init_state(0)
    wq = state.params["layers"]["attn"]["wq"]
    assert wq.sharding.spec[0] == "pp"


def test_pp_composes_with_fsdp(golden, eight_devices):
    losses, _ = run("pp_fsdp", {"pp": 2, "fsdp": 2}, pp_microbatches=2)
    np.testing.assert_allclose(losses, golden[0], rtol=2e-4)


def test_pp_composes_with_tp(golden, eight_devices):
    losses_tp, _ = run("pp_tp", {"pp": 2, "tp": 2}, pp_microbatches=2, n_devices=4)
    np.testing.assert_allclose(losses_tp, golden[0], rtol=2e-4)


def test_pp_tp_composes_with_dp(golden, eight_devices):
    # pp=2 x tp=2 x dp=2 on all 8 devices — tp is manual inside the pipeline
    # shard_map, so no XLA partitioner CHECK with a third nontrivial axis
    losses, state = run("pp_tp", {"pp": 2, "tp": 2}, pp_microbatches=2)
    np.testing.assert_allclose(losses, golden[0], rtol=2e-4)
    # atol is looser than the pure-pp golden: the vocab-parallel logsumexp
    # reorders reductions and Adam amplifies tiny grad differences
    for a, b in zip(jax.tree.leaves(jax.device_get(golden[1].params)),
                    jax.tree.leaves(jax.device_get(state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-2, atol=4e-4)


def test_pp_tp_composes_with_fsdp(golden, eight_devices):
    losses, _ = run("pp_tp_fsdp", {"pp": 2, "tp": 2, "fsdp": 2}, pp_microbatches=2)
    np.testing.assert_allclose(losses, golden[0], rtol=2e-4)


def _nested_shard_maps(jaxpr):
    """(depth-inside-pp-region, manual_axes, in_specs) for every shard_map
    nested inside the pipeline's pp-manual shard_map."""
    def subjaxprs(params):
        for v in params.values():
            vs = v if isinstance(v, (tuple, list)) else (v,)
            for w in vs:
                if hasattr(w, "jaxpr") and hasattr(w.jaxpr, "eqns"):
                    yield w.jaxpr
                elif hasattr(w, "eqns"):
                    yield w

    found = []

    def walk(jx, inside_pp):
        for eqn in jx.eqns:
            now_inside = inside_pp
            if eqn.primitive.name == "shard_map":
                axes = frozenset(eqn.params["manual_axes"])
                if inside_pp:
                    found.append((axes, eqn.params["in_specs"]))
                now_inside = inside_pp or "pp" in axes
            for sub in subjaxprs(eqn.params):
                walk(sub, now_inside)

    walk(jaxpr.jaxpr, False)
    return found


def test_pp_fsdp_flash_partitions_batch(golden, eight_devices):
    """Flash under pp (round-2 weakness closed): the sharded-flash wrapper
    nests inside the pp-manual schedule as a dp/fsdp-manual sub-region built
    against the context mesh, so the Pallas kernel runs on local batch
    shards — NOT the SPMD partitioner's gather-and-replicate fallback.
    Checks the trajectory against the single-device golden AND the program
    structure: nested batch-manual flash maps inside the pipeline region."""
    from jax.sharding import PartitionSpec as P

    bundle = get_model("llama-debug", dtype=jnp.float32)
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("pp_fsdp", make_mesh(pp=2, fsdp=2)),
                donate=False, pp_microbatches=2, attn_impl="flash")
    state = t.init_state(0)
    ids = np.random.RandomState(0).randint(0, 512, (GB, SEQ))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}

    jaxpr = jax.make_jaxpr(lambda s, b: t.step_fn(s, b))(state, batch)
    nested = [(axes, specs) for axes, specs in _nested_shard_maps(jaxpr)
              if "fsdp" in axes]
    assert nested, "no batch-manual flash shard_map nested in the pp region"
    batch_spec = P(("dp", "fsdp"), None, None, None)
    assert any(specs and specs[0] == batch_spec for _, specs in nested), \
        [s[:1] for _, s in nested]

    losses = []
    for _ in range(2):
        state, m = t.step_fn(state, batch)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, golden[0], rtol=2e-4)


@pytest.mark.parametrize("context_impl", ["ring", "ulysses"])
def test_pp_composes_with_cp(golden, eight_devices, context_impl):
    """pp x cp (round-2 gap closed): the long-context strategy and the
    pipeline are no longer mutually exclusive — the ring's / Ulysses'
    cp(+batch)-manual shard_map nests inside the pp-manual schedule (built
    against the context mesh, same mechanism as flash-under-pp), with the
    microbatch seq dim cp-sharded through the 1F1B ticks."""
    losses, _ = run("pp", {"pp": 2, "cp": 2}, pp_microbatches=2,
                    context_impl=context_impl)
    np.testing.assert_allclose(losses, golden[0], rtol=2e-4,
                               err_msg=context_impl)


def test_pp_tp_cp_three_axis(golden, eight_devices):
    """pp x tp x cp on all 8 devices: manual-tp megatron shards + the
    vocab-parallel head inside the pipeline, the ring's cp-manual shard_map
    nested under both, fully-masked ticks — the deepest manual-axis
    composition in the tree. Trajectory must match single-device."""
    losses, _ = run("pp_tp", {"pp": 2, "tp": 2, "cp": 2}, pp_microbatches=2,
                    context_impl="ring")
    np.testing.assert_allclose(losses, golden[0], rtol=2e-4)


def test_pp_cp_moe_aux_masking(eight_devices):
    """MoE under pp x cp pins the fully-masked schedule's router-aux
    cotangent path (daux * valid-mask): the dense pp x cp test never sets
    aux_coef > 0, so without this a broken masked-daux scaling would pass
    the whole suite while aux grads silently drift."""
    bundle = get_model("moe-debug", dtype=jnp.float32)
    ids = np.random.RandomState(0).randint(0, 512, (GB, SEQ))

    def run_moe(plan, **kw):
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), plan=plan,
                    donate=False, **kw)
        state = t.init_state(0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(2):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses

    golden = run_moe(make_plan("single", make_mesh(devices=jax.devices()[:1])),
                     attn_impl="xla")
    pp_cp = run_moe(make_plan("pp", make_mesh(pp=2, cp=2)),
                    pp_microbatches=2, context_impl="ring")
    np.testing.assert_allclose(pp_cp, golden, rtol=2e-4)


def test_pp_four_stages(eight_devices):
    """pp=4 (all other pp tests run pp=2): exercises the non-degenerate
    saved-input ring buffer (K = 2pp-1 = 7 > C at small M is clamped),
    longer fill/drain bubbles, and 3-hop ppermute chains — both alone and
    with the cp-masked schedule nested inside."""
    bundle4 = get_model("llama-debug", dtype=jnp.float32, num_layers=4)
    golden4, _ = run("single", {}, bundle=bundle4)
    losses, _ = run("pp", {"pp": 4}, pp_microbatches=4, bundle=bundle4)
    np.testing.assert_allclose(losses, golden4, rtol=2e-4)
    losses, _ = run("pp", {"pp": 4, "cp": 2}, pp_microbatches=4,
                    bundle=bundle4, context_impl="ring")
    np.testing.assert_allclose(losses, golden4, rtol=2e-4)


def test_pp_gpt2_family(eight_devices):
    # gpt2 exercises tied embeddings + learned position embeddings through
    # the embed/head vjp paths; under pp x tp also the column-sharded fused
    # QKV ([l,e,3,e] layout), sharded biases, and the tied vocab-parallel head
    bundle = get_model("gpt2-debug", dtype=jnp.float32)
    golden_t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                       plan=make_plan("single", make_mesh(devices=jax.devices()[:1])),
                       donate=False)
    gstate = golden_t.init_state(0)
    ids = np.random.RandomState(0).randint(0, 512, (GB, SEQ))
    gbatch = {k: jax.device_put(jnp.asarray(ids), golden_t.batch_shardings()[k])
              for k in ("input_ids", "labels")}
    glosses = [float(golden_t.step_fn(gstate, gbatch)[1]["loss"])]

    for strategy, mesh_kw in (("pp", {"pp": 2}), ("pp_tp", {"pp": 2, "tp": 2})):
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                    plan=make_plan(strategy, make_mesh(**mesh_kw)), donate=False,
                    pp_microbatches=2)
        state = t.init_state(0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = [float(t.step_fn(state, batch)[1]["loss"])]
        np.testing.assert_allclose(losses, glosses, rtol=2e-4, err_msg=strategy)


def test_pp_neox_family(eight_devices):
    """NeoX under the 1F1B schedule: the parallel-residual block inside a
    pipeline stage, and under pp x tp the manual-tp path where BOTH
    row-parallel partial sums (attention out-proj + MLP down-proj) share a
    single psum — plus the untied vocab-parallel head."""
    bundle = get_model("neox-debug", dtype=jnp.float32)
    golden_t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                       plan=make_plan("single", make_mesh(devices=jax.devices()[:1])),
                       donate=False)
    gstate = golden_t.init_state(0)
    ids = np.random.RandomState(0).randint(0, 512, (GB, SEQ))
    gbatch = {k: jax.device_put(jnp.asarray(ids), golden_t.batch_shardings()[k])
              for k in ("input_ids", "labels")}
    glosses = [float(golden_t.step_fn(gstate, gbatch)[1]["loss"])]

    for strategy, mesh_kw in (("pp", {"pp": 2}), ("pp_tp", {"pp": 2, "tp": 2})):
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                    plan=make_plan(strategy, make_mesh(**mesh_kw)), donate=False,
                    pp_microbatches=2)
        state = t.init_state(0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = [float(t.step_fn(state, batch)[1]["loss"])]
        np.testing.assert_allclose(losses, glosses, rtol=2e-4, err_msg=strategy)


def test_flat_rmsnorm_manual_tp_matches_full_width(eight_devices):
    """The OLMo-2 full-width q/k RMSNorm under MANUAL tp: the statistic is
    a reduction over the sharded heads dim, so the psum'd sum-of-squares
    must reproduce the unsharded norm EXACTLY. x is deliberately
    anisotropic across the shard boundary (first half scaled 3x) so a
    shard-local mean cannot masquerade as the global one."""
    from jax.sharding import PartitionSpec as P
    from distributed_training_guide_tpu.models.llama import (_flat_rmsnorm,
                                                             _rmsnorm)
    from distributed_training_guide_tpu.parallel import make_mesh

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 4, 64), jnp.float32)
    x = x.at[..., :32].multiply(3.0)          # local stats != global stats
    scale = jnp.asarray(1.0 + 0.1 * rng.randn(64), jnp.float32)
    mesh = make_mesh(tp=2, devices=jax.devices()[:2])

    manual = jax.jit(jax.shard_map(
        lambda xs, ss: _flat_rmsnorm(xs, ss, 1e-5, "tp"),
        mesh=mesh, in_specs=(P(None, None, "tp"), P("tp")),
        out_specs=P(None, None, "tp")))(x, scale)
    ref = _rmsnorm(x, scale, 1e-5)
    np.testing.assert_allclose(np.asarray(manual), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
    # and the shard-local statistic really WOULD diverge (test has teeth)
    local = jax.jit(jax.shard_map(
        lambda xs, ss: _rmsnorm(xs, ss, 1e-5),
        mesh=mesh, in_specs=(P(None, None, "tp"), P("tp")),
        out_specs=P(None, None, "tp")))(x, scale)
    assert np.abs(np.asarray(local) - np.asarray(ref)).max() > 0.1


def test_pp_olmo2_family(eight_devices):
    """OLMo-2 under the 1F1B schedule, incl. pp x tp MANUAL megatron
    shards: the full-width q/k RMSNorm is a reduction over the heads dim,
    which tp shards — the psum'd sum-of-squares (_flat_rmsnorm) must make
    the manual-tp trajectory match single-device exactly (a shard-local
    mean would silently diverge here)."""
    bundle = get_model("olmo2-7b", vocab_size=512, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, max_position_embeddings=256,
                       dtype=jnp.float32)
    assert bundle.config.post_norm and bundle.config.qk_norm == "flat"
    golden_t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                       plan=make_plan("single",
                                      make_mesh(devices=jax.devices()[:1])),
                       donate=False)
    gstate = golden_t.init_state(0)
    ids = np.random.RandomState(0).randint(0, 512, (GB, SEQ))
    gbatch = {k: jax.device_put(jnp.asarray(ids), golden_t.batch_shardings()[k])
              for k in ("input_ids", "labels")}
    glosses = [float(golden_t.step_fn(gstate, gbatch)[1]["loss"])]

    for strategy, mesh_kw in (("pp", {"pp": 2}), ("pp_tp", {"pp": 2, "tp": 2})):
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                    plan=make_plan(strategy, make_mesh(**mesh_kw)), donate=False,
                    pp_microbatches=2)
        state = t.init_state(0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = [float(t.step_fn(state, batch)[1]["loss"])]
        np.testing.assert_allclose(losses, glosses, rtol=2e-4, err_msg=strategy)


def test_pp_qwen3_family(eight_devices):
    """Qwen3 under the 1F1B schedule incl. manual megatron tp: the per-head
    [head_dim] q/k norm scales are REPLICATED across tp members (the norm
    reduces over the unsharded head_dim), so the manual path needs no
    collective — trajectory must still match single-device."""
    bundle = get_model("qwen3-0.6b", vocab_size=512, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, head_dim=16,
                       max_position_embeddings=256, dtype=jnp.float32)
    assert bundle.config.qk_norm is True
    golden_t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                       plan=make_plan("single",
                                      make_mesh(devices=jax.devices()[:1])),
                       donate=False)
    gstate = golden_t.init_state(0)
    ids = np.random.RandomState(0).randint(0, 512, (GB, SEQ))
    gbatch = {k: jax.device_put(jnp.asarray(ids), golden_t.batch_shardings()[k])
              for k in ("input_ids", "labels")}
    glosses = [float(golden_t.step_fn(gstate, gbatch)[1]["loss"])]

    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("pp_tp", make_mesh(pp=2, tp=2)), donate=False,
                pp_microbatches=2)
    state = t.init_state(0)
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    losses = [float(t.step_fn(state, batch)[1]["loss"])]
    np.testing.assert_allclose(losses, glosses, rtol=2e-4)


def test_pp_moe_family(eight_devices):
    """MoE under the 1F1B schedule: router aux loss flows through the
    per-tick vjp (cotangent on the stage's aux output) and the trajectory
    matches the single-device MoE run."""
    bundle = get_model("moe-debug", dtype=jnp.float32)
    ids = np.random.RandomState(0).randint(0, 512, (GB, SEQ))

    def run_moe(plan, **kw):
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), plan=plan,
                    donate=False, attn_impl="xla", **kw)
        state = t.init_state(0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(2):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses

    golden = run_moe(make_plan("single", make_mesh(devices=jax.devices()[:1])))
    pp = run_moe(make_plan("pp", make_mesh(pp=2)), pp_microbatches=2)
    np.testing.assert_allclose(pp, golden, rtol=2e-4)


@pytest.mark.parametrize("model,coef", [("llama-debug", None),
                                        ("moe-debug", 1.0),
                                        ("gpt2-debug", None)])
def test_pp_tp_grad_parity(eight_devices, model, coef):
    """pp x tp gradients must equal the single-device gradients EXACTLY (not
    just up to a scale — Adam is invariant to uniform grad scaling, so the
    trajectory goldens above cannot catch a tp x factor, but grad_norm,
    clipping, and plain SGD all can). The reference is the per-microbatch
    mean loss, matching the schedule's aux semantics. Covers the vocab-
    parallel head (psum-transposes-to-psum cotangent scaling) and, for moe,
    the tp-redundant router aux path."""
    from distributed_training_guide_tpu.ops.cross_entropy import causal_lm_loss
    from distributed_training_guide_tpu.parallel.pipeline import (
        make_pipeline_value_and_grad)

    kw = {"dtype": jnp.float32}
    if coef is not None:
        kw["router_aux_coef"] = coef
    bundle = get_model(model, **kw)
    cfg = bundle.config
    M = 2
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 512, (GB, SEQ)))
    params = jax.jit(lambda: bundle.init(cfg, jax.random.key(0)))()

    def ref_loss(p):
        tot = 0.0
        for m in range(M):
            chunk = ids[m * (GB // M):(m + 1) * (GB // M)]
            if bundle.apply_with_aux is not None:
                logits, aux = bundle.apply_with_aux(cfg, p, chunk, attn_impl="xla")
                tot += causal_lm_loss(logits, chunk) + cfg.router_aux_coef * aux
            else:
                tot += causal_lm_loss(
                    bundle.apply(cfg, p, chunk, attn_impl="xla"), chunk)
        return tot / M

    ref_l, ref_g = jax.jit(jax.value_and_grad(ref_loss))(params)

    plan = make_plan("pp_tp", make_mesh(pp=2, tp=2, devices=jax.devices()[:4]))
    vag = make_pipeline_value_and_grad(bundle, plan, microbatches=M,
                                       attn_impl="xla")
    shardings = plan.param_shardings(
        bundle.param_logical_axes(cfg),
        jax.eval_shape(lambda: bundle.init(cfg, jax.random.key(0))))
    l, g = jax.jit(vag)(jax.device_put(params, shardings),
                        {"input_ids": ids, "labels": ids})

    np.testing.assert_allclose(float(l), float(ref_l), rtol=1e-6)
    for (path, r), p in zip(jax.tree_util.tree_flatten_with_path(ref_g)[0],
                            jax.tree.leaves(g)):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(p)), np.asarray(r), rtol=5e-3, atol=1e-5,
            err_msg=jax.tree_util.keystr(path))


def test_pp_tp_moe_trajectory(eight_devices):
    """pp=2 x tp=2 x dp=2 with the MoE family: megatron expert-FFN shards +
    vocab-parallel embed/head, trajectory matches single-device."""
    bundle = get_model("moe-debug", dtype=jnp.float32)
    ids = np.random.RandomState(0).randint(0, 512, (GB, SEQ))

    def run_moe(plan, **kw):
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), plan=plan,
                    donate=False, attn_impl="xla", **kw)
        state = t.init_state(0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(2):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses

    golden = run_moe(make_plan("single", make_mesh(devices=jax.devices()[:1])))
    pp_tp = run_moe(make_plan("pp_tp", make_mesh(pp=2, tp=2)),
                    pp_microbatches=2)
    np.testing.assert_allclose(pp_tp, golden, rtol=2e-4)


def test_pp_with_loss_chunks(golden, eight_devices):
    # chunked CE on the last stage: same trajectory, no [mb,S,V] logits
    bundle = get_model("llama-debug", dtype=jnp.float32)
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("pp", make_mesh(pp=2)), donate=False,
                pp_microbatches=2, loss_chunks=4)
    state = t.init_state(0)
    ids = np.random.RandomState(0).randint(0, 512, (GB, SEQ))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    losses = []
    for _ in range(2):
        state, m = t.step_fn(state, batch)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, golden[0], rtol=2e-4)


def test_pp_rejects_per_layer_windows_pinned_contract(eight_devices):
    """The documented pp x layer_windows contract (09-pipeline-parallel
    README "Known limits"): traced per-layer window schedules (Gemma-2's
    alternating pattern) are NOT plumbed through the pipeline's manual
    region — construction must fail loudly, naming the limitation and the
    supported plans, BEFORE any compile. A UNIFORM sliding window has no
    traced per-layer column and stays accepted under pp."""
    lw_bundle = get_model("llama-debug", dtype=jnp.float32,
                          layer_windows=(16, 0))
    with pytest.raises(ValueError,
                       match="layer_windows.*pipeline|pipeline.*layer_win"):
        Trainer(bundle=lw_bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("pp", make_mesh(pp=2)), donate=False,
                pp_microbatches=2)
    # same config on a cp plan (the composing case) constructs fine
    Trainer(bundle=lw_bundle, optimizer=adamw_cosine(1e-3),
            plan=make_plan("ddp", make_mesh(cp=2)), donate=False)
    # uniform window under pp: accepted (no per-layer column involved)
    sw_bundle = get_model("llama-debug", dtype=jnp.float32,
                          sliding_window=16)
    Trainer(bundle=sw_bundle, optimizer=adamw_cosine(1e-3),
            plan=make_plan("pp", make_mesh(pp=2)), donate=False,
            pp_microbatches=2)
