"""Pipeline-parallel parity tests on the virtual 8-device mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine

GB = 8
SEQ = 32


def run(strategy, mesh_kw, pp_microbatches=None, steps=2, n_devices=None):
    bundle = get_model("llama-debug", dtype=jnp.float32)
    if strategy == "single":
        mesh = make_mesh(devices=jax.devices()[:1])
    else:
        devices = jax.devices()[:n_devices] if n_devices else None
        mesh = make_mesh(devices=devices, **mesh_kw)
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan(strategy, mesh), donate=False,
                pp_microbatches=pp_microbatches)
    state = t.init_state(0)
    ids = np.random.RandomState(0).randint(0, 512, (GB, SEQ))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    losses = []
    for _ in range(steps):
        state, m = t.step_fn(state, batch)
        losses.append(float(m["loss"]))
    return losses, state


@pytest.fixture(scope="module")
def golden():
    return run("single", {})


def test_pp_matches_single(golden, eight_devices):
    # llama-debug has 2 layers -> pp=2 stages of 1 layer; dp=4 so the
    # microbatch (GB/M = 4) must stay divisible by dp
    losses, state = run("pp", {"pp": 2}, pp_microbatches=2)
    np.testing.assert_allclose(losses, golden[0], rtol=2e-4)
    for a, b in zip(jax.tree.leaves(jax.device_get(golden[1].params)),
                    jax.tree.leaves(jax.device_get(state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-2, atol=1e-4)


def test_pp_params_sharded(eight_devices):
    bundle = get_model("llama-debug", dtype=jnp.float32)
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("pp", make_mesh(pp=2)), donate=False)
    state = t.init_state(0)
    wq = state.params["layers"]["attn"]["wq"]
    assert wq.sharding.spec[0] == "pp"


def test_pp_composes_with_fsdp(golden, eight_devices):
    losses, _ = run("pp_fsdp", {"pp": 2, "fsdp": 2}, pp_microbatches=2)
    np.testing.assert_allclose(losses, golden[0], rtol=2e-4)


def test_pp_composes_with_tp(golden, eight_devices):
    # pp x tp needs dp == fsdp == 1 (XLA partitioner limitation) -> 4-device
    # submesh
    losses_tp, _ = run("pp_tp", {"pp": 2, "tp": 2}, pp_microbatches=2, n_devices=4)
    np.testing.assert_allclose(losses_tp, golden[0], rtol=2e-4)


def test_pp_tp_with_dp_raises(eight_devices):
    with pytest.raises(NotImplementedError):
        run("pp_tp", {"pp": 2, "tp": 2}, pp_microbatches=2)  # dp=2 -> unsupported
