"""Model zoo unit tests: shapes, determinism, gradient flow, param counts."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.ops import causal_lm_loss


@pytest.mark.parametrize("name", ["gpt2-debug", "llama-debug", "neox-debug"])
def test_forward_shapes_and_determinism(name):
    bundle = get_model(name)
    params = bundle.init(bundle.config, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, bundle.config.vocab_size)
    logits = bundle.apply(bundle.config, params, ids)
    assert logits.shape == (2, 16, bundle.config.vocab_size)
    assert logits.dtype == jnp.float32
    logits2 = bundle.apply(bundle.config, params, ids)
    np.testing.assert_array_equal(np.asarray(logits), np.asarray(logits2))


@pytest.mark.parametrize("name", ["gpt2-debug", "llama-debug", "neox-debug"])
def test_causality(name):
    """Changing a future token must not affect past logits."""
    bundle = get_model(name)
    params = bundle.init(bundle.config, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 12), 0, bundle.config.vocab_size)
    ids2 = ids.at[0, -1].set((ids[0, -1] + 1) % bundle.config.vocab_size)
    a = bundle.apply(bundle.config, params, ids)
    b = bundle.apply(bundle.config, params, ids2)
    np.testing.assert_allclose(np.asarray(a[:, :-1]), np.asarray(b[:, :-1]), atol=2e-2)


@pytest.mark.parametrize("name", ["gpt2-debug", "llama-debug", "neox-debug"])
def test_grads_nonzero(name):
    bundle = get_model(name)
    params = bundle.init(bundle.config, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, bundle.config.vocab_size)

    def loss_fn(p):
        return causal_lm_loss(bundle.apply(bundle.config, p, ids), ids)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
    assert all(np.isfinite(n) for n in norms)
    assert sum(n > 0 for n in norms) >= len(norms) - 2  # norms may be ~0 early


@pytest.mark.parametrize("name", ["gpt2", "llama-3.1-8b", "llama-3.1-405b", "pythia-1.4b", "gpt-neox-20b"])
def test_param_count_formula(name):
    """num_params() formula matches the known public sizes within 1%."""
    known = {"gpt2": 124e6, "llama-3.1-8b": 8.03e9, "llama-3.1-405b": 405.8e9,
             "pythia-1.4b": 1.41e9, "gpt-neox-20b": 20.6e9}
    bundle = get_model(name)
    assert abs(bundle.num_params() - known[name]) / known[name] < 0.01


def test_remat_matches_no_remat():
    bundle = get_model("llama-debug")
    params = bundle.init(bundle.config, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 16), 0, bundle.config.vocab_size)

    def loss_fn(p, remat):
        return causal_lm_loss(bundle.apply(bundle.config, p, ids, remat=remat), ids)

    g1 = jax.grad(lambda p: loss_fn(p, False))(params)
    g2 = jax.grad(lambda p: loss_fn(p, True))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        # bf16 activations: recompute order differs under remat, so allow
        # one-bf16-ulp noise.
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-2, atol=5e-3)


def test_remat_policies_match_no_remat():
    """Every named policy ("all"/"dots"/"attn") is a pure memory/time trade —
    gradients must match the no-remat program (attn relies on the
    checkpoint_name tags in ops/attention.py + ops/flash_attention.py)."""
    from distributed_training_guide_tpu.train.step import REMAT_POLICIES

    bundle = get_model("llama-debug")
    params = bundle.init(bundle.config, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 16), 0, bundle.config.vocab_size)

    def grads(**kw):
        return jax.grad(lambda p: causal_lm_loss(
            bundle.apply(bundle.config, p, ids, **kw), ids))(params)

    ref = grads(remat=False)
    for name, policy in REMAT_POLICIES.items():
        got = grads(remat=True, remat_policy=policy)
        for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-2, atol=5e-3, err_msg=name)


def test_logical_axes_mirror_params():
    for name in ["gpt2-debug", "llama-debug"]:
        bundle = get_model(name)
        params = bundle.init(bundle.config, jax.random.key(0))
        axes = bundle.param_logical_axes(bundle.config)
        p_struct = jax.tree.structure(params)
        a_struct = jax.tree.structure(axes, is_leaf=lambda x: isinstance(x, tuple))
        assert p_struct == a_struct
        for leaf, ax in zip(jax.tree.leaves(params),
                            jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))):
            assert leaf.ndim == len(ax), f"{name}: {leaf.shape} vs {ax}"


def test_init_fingerprints_are_stable():
    """The determinism CONTRACT: same seed -> same params across releases.
    The round-5 family refactors silently reordered init's jax.random key
    draws once (caught by a borderline tolerance failure, bisected, fixed);
    these committed fingerprints turn any future reorder into a direct,
    named failure instead. Values computed at the fixed seed on the debug
    presets (leaf-sum is order-sensitive through the key split)."""
    import jax
    import numpy as np

    from distributed_training_guide_tpu.models import get_model

    expected = {
        "llama-debug": 322.347783,
        "moe-debug": 322.682622,
        "gpt2-debug": 316.355518,
        "neox-debug": 312.050139,
    }
    for name, want in expected.items():
        b = get_model(name)
        p = b.init(b.config, jax.random.key(0))
        got = sum(float(np.asarray(leaf, np.float64).sum())
                  for leaf in jax.tree.leaves(p))
        np.testing.assert_allclose(got, want, rtol=1e-6, err_msg=name)
