"""MoE model + expert-parallel plan tests."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.ops import causal_lm_loss
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine


def test_moe_forward_and_grads():
    bundle = get_model("moe-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, bundle.config.vocab_size)
    logits, aux = bundle.apply_with_aux(bundle.config, params, ids)
    assert logits.shape == (2, 16, bundle.config.vocab_size)
    # aux >= 1 for any routing (equals num_experts * sum f_e p_e >= 1)
    assert float(aux) >= 0.99

    def loss_fn(p):
        lg, ax = bundle.apply_with_aux(bundle.config, p, ids)
        return causal_lm_loss(lg, ids) + 0.01 * ax

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    # router must receive gradient (routing is differentiable through combine)
    g_router = grads["layers"]["moe"]["router"]
    assert float(jnp.linalg.norm(g_router)) > 0


def test_moe_capacity_drops_are_bounded():
    """With capacity_factor >= num_experts every token fits (no drops):
    output must equal a full-capacity run."""
    bundle_small = get_model("moe-debug", dtype=jnp.float32, capacity_factor=8.0)
    bundle_huge = get_model("moe-debug", dtype=jnp.float32, capacity_factor=16.0)
    params = bundle_small.init(bundle_small.config, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 16), 0, 512)
    a, _ = bundle_small.apply_with_aux(bundle_small.config, params, ids)
    b, _ = bundle_huge.apply_with_aux(bundle_huge.config, params, ids)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_moe_overflow_tokens_get_zero_output():
    """Force every token onto one expert with capacity 2: exactly the first 2
    tokens (greedy order) get expert output; overflow rows are exactly zero
    (they fall through on the residual)."""
    from distributed_training_guide_tpu.models.moe import _moe_ffn

    bundle = get_model("moe-debug", dtype=jnp.float32, experts_per_token=1,
                       capacity_factor=0.5)  # C = ceil(0.5 * 16 / 4) = 2
    cfg = bundle.config
    d, f, ex = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    rng = jax.random.key(0)
    router = jnp.zeros((d, ex)).at[:, 0].set(1.0)  # all tokens -> expert 0
    moe_params = {
        "router": router,
        "gate": jax.random.normal(rng, (ex, d, f)) * 0.02,
        "up": jax.random.normal(rng, (ex, d, f)) * 0.02,
        "down": jax.random.normal(rng, (ex, f, d)) * 0.02,
    }
    x = jnp.ones((1, 16, d))
    y, _, dropped = _moe_ffn(cfg, x, moe_params)
    y = np.asarray(y)[0]
    norms = np.linalg.norm(y, axis=-1)
    assert (norms[:2] > 0).all(), "in-capacity tokens must get expert output"
    np.testing.assert_array_equal(norms[2:], 0.0)
    np.testing.assert_allclose(float(dropped), 14 / 16, rtol=1e-6)


def test_ep_matches_single_device(eight_devices):
    bundle = get_model("moe-debug", dtype=jnp.float32)
    opt = adamw_cosine(1e-3)
    ids = np.random.RandomState(0).randint(0, 512, (8, 32))

    def run(plan):
        t = Trainer(bundle=bundle, optimizer=opt, plan=plan, donate=False)
        state = t.init_state(0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(2):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses, state

    golden, _ = run(make_plan("single", make_mesh(devices=jax.devices()[:1])))
    ep_losses, state = run(make_plan("ep", make_mesh(ep=4)))
    np.testing.assert_allclose(ep_losses, golden, rtol=2e-4)
    gate = state.params["layers"]["moe"]["gate"]
    assert gate.sharding.spec[1] == "ep"  # expert dim sharded

    ep_fsdp, _ = run(make_plan("ep_fsdp", make_mesh(ep=2, fsdp=2)))
    np.testing.assert_allclose(ep_fsdp, golden, rtol=2e-4)


def test_ep_dispatch_stays_local(eight_devices):
    """HLO-level locality proof for the index-based dispatch (the weight
    sharding + loss-trajectory checks above would NOT fail if GSPMD silently
    gathered the [E,C,D] buffers or the expert weights around the scatter —
    the silent-replication failure class the sharded-flash wrapper fixed).
    At E=8, ep=8: the compiled program must hold only E/ep-local expert
    buffers and weight shards on any device — the full-E shapes appearing
    anywhere means gather-and-replicate, which is also the per-device
    memory guarantee (1/ep buffers + weights, not Ex)."""
    import math

    bundle = get_model("moe-debug", dtype=jnp.float32, num_experts=8)
    cfg = bundle.config
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("ep", make_mesh(ep=8)), donate=False,
                attn_impl="xla")
    state = t.init_state(0)
    b, s = 8, 32
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (b, s))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    hlo = jax.jit(t.step_fn).lower(state, batch).compile().as_text()

    E, D, F, L = (cfg.num_experts, cfg.hidden_size, cfg.intermediate_size,
                  cfg.num_layers)
    C = max(int(math.ceil(cfg.capacity_factor * cfg.experts_per_token
                          * b * s / E)), 1)
    # local (E/ep = 1) expert compute is present — the [1, C, F] inner
    # activation must materialize around the silu*up elementwise. (The
    # [1, C, D] INPUT buffer is no longer asserted: the gather-only
    # dispatch fuses it into the expert einsum, so it never exists as a
    # standalone tensor — that fusion is the point of the formulation.)
    assert f"f32[1,{C},{F}]" in hlo, "no ep-local expert activation in HLO"
    # ...and no device ever materializes the full-E dispatch/activation
    # buffers or the full expert-weight stacks (params, grads, or moments)
    for full in (f"f32[{E},{C},{D}]", f"f32[{E},{C},{F}]",
                 f"f32[{L},{E},{D},{F}]", f"f32[{L},{E},{F},{D}]"):
        assert full not in hlo, f"full-E tensor {full} in compiled HLO"
