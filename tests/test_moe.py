"""MoE model + expert-parallel plan tests."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.ops import causal_lm_loss
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine
from distributed_training_guide_tpu.utils import hlo as hlo_util


def test_moe_forward_and_grads():
    bundle = get_model("moe-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, bundle.config.vocab_size)
    logits, aux = bundle.apply_with_aux(bundle.config, params, ids)
    assert logits.shape == (2, 16, bundle.config.vocab_size)
    # aux >= 1 for any routing (equals num_experts * sum f_e p_e >= 1)
    assert float(aux) >= 0.99

    def loss_fn(p):
        lg, ax = bundle.apply_with_aux(bundle.config, p, ids)
        return causal_lm_loss(lg, ids) + 0.01 * ax

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    # router must receive gradient (routing is differentiable through combine)
    g_router = grads["layers"]["moe"]["router"]
    assert float(jnp.linalg.norm(g_router)) > 0


def test_moe_capacity_drops_are_bounded():
    """With capacity_factor >= num_experts every token fits (no drops):
    output must equal a full-capacity run."""
    bundle_small = get_model("moe-debug", dtype=jnp.float32, capacity_factor=8.0)
    bundle_huge = get_model("moe-debug", dtype=jnp.float32, capacity_factor=16.0)
    params = bundle_small.init(bundle_small.config, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 16), 0, 512)
    a, _ = bundle_small.apply_with_aux(bundle_small.config, params, ids)
    b, _ = bundle_huge.apply_with_aux(bundle_huge.config, params, ids)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_moe_overflow_tokens_get_zero_output():
    """Force every token onto one expert with capacity 2: exactly the first 2
    tokens (greedy order) get expert output; overflow rows are exactly zero
    (they fall through on the residual)."""
    from distributed_training_guide_tpu.models.moe import _moe_ffn

    bundle = get_model("moe-debug", dtype=jnp.float32, experts_per_token=1,
                       capacity_factor=0.5)  # C = ceil(0.5 * 16 / 4) = 2
    cfg = bundle.config
    d, f, ex = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    rng = jax.random.key(0)
    router = jnp.zeros((d, ex)).at[:, 0].set(1.0)  # all tokens -> expert 0
    moe_params = {
        "router": router,
        "gate": jax.random.normal(rng, (ex, d, f)) * 0.02,
        "up": jax.random.normal(rng, (ex, d, f)) * 0.02,
        "down": jax.random.normal(rng, (ex, f, d)) * 0.02,
    }
    x = jnp.ones((1, 16, d))
    y, _, dropped = _moe_ffn(cfg, x, moe_params)
    y = np.asarray(y)[0]
    norms = np.linalg.norm(y, axis=-1)
    assert (norms[:2] > 0).all(), "in-capacity tokens must get expert output"
    np.testing.assert_array_equal(norms[2:], 0.0)
    np.testing.assert_allclose(float(dropped), 14 / 16, rtol=1e-6)


def test_ep_matches_single_device(eight_devices):
    """Params are created once and fed to every trainer: under a
    vocab/embed-sharded mesh the sharded init RNG draws different embedding
    values than single-device (non-partitionable threefry under GSPMD),
    which is init noise, not dispatch error — sharing the params pins the
    thing this test is about (the ep dispatch math) and lets the tolerance
    stay tight."""
    bundle = get_model("moe-debug", dtype=jnp.float32)
    opt = adamw_cosine(1e-3)
    ids = np.random.RandomState(0).randint(0, 512, (8, 32))
    params = bundle.init(bundle.config, jax.random.key(0))

    def run(plan):
        t = Trainer(bundle=bundle, optimizer=opt, plan=plan, donate=False)
        state = t.init_state_from_params(jax.device_put(params), 0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(2):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses, state

    golden, _ = run(make_plan("single", make_mesh(devices=jax.devices()[:1])))
    ep_losses, state = run(make_plan("ep", make_mesh(ep=4)))
    np.testing.assert_allclose(ep_losses, golden, rtol=2e-4)
    gate = state.params["layers"]["moe"]["gate"]
    assert gate.sharding.spec[1] == "ep"  # expert dim sharded

    ep_fsdp, _ = run(make_plan("ep_fsdp", make_mesh(ep=2, fsdp=2)))
    np.testing.assert_allclose(ep_fsdp, golden, rtol=2e-4)


def test_ep_dispatch_stays_local(eight_devices):
    """HLO-level locality proof for the index-based dispatch (the weight
    sharding + loss-trajectory checks above would NOT fail if GSPMD silently
    gathered the [E,C,D] buffers or the expert weights around the scatter —
    the silent-replication failure class the sharded-flash wrapper fixed).
    At E=8, ep=8: the compiled program must hold only E/ep-local expert
    buffers and weight shards on any device — the full-E shapes appearing
    anywhere means gather-and-replicate, which is also the per-device
    memory guarantee (1/ep buffers + weights, not Ex)."""
    import math

    bundle = get_model("moe-debug", dtype=jnp.float32, num_experts=8)
    cfg = bundle.config
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("ep", make_mesh(ep=8)), donate=False,
                attn_impl="xla")
    state = t.init_state(0)
    b, s = 8, 32
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (b, s))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    hlo = jax.jit(t.step_fn).lower(state, batch).compile().as_text()

    E, D, F, L = (cfg.num_experts, cfg.hidden_size, cfg.intermediate_size,
                  cfg.num_layers)
    C = max(int(math.ceil(cfg.capacity_factor * cfg.experts_per_token
                          * b * s / E)), 1)
    # local (E/ep = 1) expert compute is present — the [1, C, F] inner
    # activation must materialize around the silu*up elementwise. (The
    # [1, C, D] INPUT buffer is no longer asserted: the gather-only
    # dispatch fuses it into the expert einsum, so it never exists as a
    # standalone tensor — that fusion is the point of the formulation.)
    assert hlo_util.has_aval(hlo, "f32", (1, C, F)), \
        "no ep-local expert activation in HLO"
    # ...and no device ever materializes the full-E dispatch/activation
    # buffers or the full expert-weight stacks (params, grads, or moments)
    for full in ((E, C, D), (E, C, F), (L, E, D, F), (L, E, F, D)):
        assert not hlo_util.has_aval(hlo, "f32", full), \
            f"full-E tensor f32{list(full)} in compiled HLO"


# ---------------------------------------------------------------------------
# dropless ragged dispatch (moe_dispatch="ragged", PR 3)
# ---------------------------------------------------------------------------

@pytest.mark.grouped
def test_ragged_matches_dense_loss_trajectory():
    """Acceptance pin: with capacity_factor high enough that dense drops
    nothing, the ragged backend must track the dense loss trajectory within
    1e-5 relative over 20 optimizer steps (same seed, same data) — the two
    dispatches are then the same math, reassociated."""
    opt = adamw_cosine(1e-3)
    ids = np.random.RandomState(7).randint(0, 512, (4, 32))

    def run(dispatch):
        bundle = get_model("moe-debug", dtype=jnp.float32,
                           capacity_factor=8.0, moe_dispatch=dispatch)
        t = Trainer(bundle=bundle, optimizer=opt,
                    plan=make_plan("single",
                                   make_mesh(devices=jax.devices()[:1])),
                    donate=False)
        state = t.init_state(0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses, dropped = [], []
        for _ in range(20):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
            dropped.append(float(m["moe_dropped_frac"]))
        return losses, dropped

    dense_losses, dense_dropped = run("dense")
    ragged_losses, ragged_dropped = run("ragged")
    assert max(dense_dropped) == 0.0  # precondition: dense dropped nothing
    np.testing.assert_allclose(ragged_losses, dense_losses, rtol=1e-5)
    assert ragged_dropped == [0.0] * 20


@pytest.mark.grouped
def test_ragged_dropped_frac_zero_even_when_dense_drops():
    """dropped_frac must be identically 0 under ragged dispatch — even at a
    capacity_factor where the dense backend drops most pairs (capacity is
    simply not a ragged concept), and every token must get expert output."""
    from distributed_training_guide_tpu.models.moe import _moe_ffn

    dense = get_model("moe-debug", dtype=jnp.float32, experts_per_token=1,
                      capacity_factor=0.5)
    ragged = get_model("moe-debug", dtype=jnp.float32, experts_per_token=1,
                       capacity_factor=0.5, moe_dispatch="ragged")
    params = dense.init(dense.config, jax.random.key(0))
    moe_layer0 = jax.tree.map(lambda x: x[0], params["layers"]["moe"])
    x = jax.random.normal(jax.random.key(1), (1, 16, dense.config.hidden_size))
    _, _, d_dense = _moe_ffn(dense.config, x, moe_layer0)
    y, _, d_ragged = _moe_ffn(ragged.config, x, moe_layer0)
    assert float(d_dense) >= 0.5         # dense is actually dropping here
    assert float(d_ragged) == 0.0
    norms = np.linalg.norm(np.asarray(y)[0], axis=-1)
    assert (norms > 0).all(), "dropless: every token gets expert output"


@pytest.mark.grouped
def test_ep_ragged_matches_single_device(eight_devices):
    """ep / ep x fsdp ragged runs (the shard_map'd sorted-group exchange)
    must reproduce the single-device ragged trajectory. Params are created
    once and fed to every trainer: sharded RNG makes vocab-sharded init
    draw different values (pre-existing; the dense test absorbs it in its
    tolerance), and this test pins the *dispatch* math, not the init."""
    bundle = get_model("moe-debug", dtype=jnp.float32, moe_dispatch="ragged")
    opt = adamw_cosine(1e-3)
    ids = np.random.RandomState(0).randint(0, 512, (8, 32))
    params = bundle.init(bundle.config, jax.random.key(0))

    def run(plan):
        t = Trainer(bundle=bundle, optimizer=opt, plan=plan, donate=False)
        state = t.init_state_from_params(jax.device_put(params), 0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(3):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses, m, state

    golden, _, _ = run(make_plan("single", make_mesh(devices=jax.devices()[:1])))
    ep_losses, m, state = run(make_plan("ep", make_mesh(ep=4)))
    np.testing.assert_allclose(ep_losses, golden, rtol=2e-5)
    assert float(m["moe_dropped_frac"]) == 0.0
    gate = state.params["layers"]["moe"]["gate"]
    assert gate.sharding.spec[1] == "ep"   # expert dim stays ep-sharded

    epf_losses, _, _ = run(make_plan("ep_fsdp", make_mesh(ep=2, fsdp=2)))
    np.testing.assert_allclose(epf_losses, golden, rtol=2e-5)


@pytest.mark.grouped
def test_ep_ragged_keeps_expert_stacks_local(eight_devices):
    """Compiled-HLO locality proof for the ragged backend, mirroring
    test_ep_dispatch_stays_local: at E=8, ep=8 no device may materialize
    the full expert weight stacks (params, grads, or moments) — the
    sorted-group exchange must keep grouped GEMMs on E/ep-local shards."""
    bundle = get_model("moe-debug", dtype=jnp.float32, num_experts=8,
                       moe_dispatch="ragged")
    cfg = bundle.config
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("ep", make_mesh(ep=8)), donate=False,
                attn_impl="xla")
    state = t.init_state(0)
    b, s = 8, 32
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (b, s))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    hlo = jax.jit(t.step_fn).lower(state, batch).compile().as_text()

    E, D, F, L = (cfg.num_experts, cfg.hidden_size, cfg.intermediate_size,
                  cfg.num_layers)
    # local (E/ep = 1) expert weight shards are what the device holds (the
    # per-layer slice fuses into the scan body, so assert the stacked form)
    assert hlo_util.has_aval(hlo, "f32", (L, 1, D, F)), \
        "no ep-local expert stack in HLO"
    for full in ((L, E, D, F), (L, E, F, D), (E, D, F), (E, F, D)):
        assert not hlo_util.has_aval(hlo, "f32", full), \
            f"full-E tensor f32{list(full)} in compiled HLO"


@pytest.mark.grouped
def test_decode_no_drop_transients_scale_with_tokens():
    """Acceptance pin for the decode-path memory fix: lowering qwen1.5-moe
    prefill at T=2048 must show O(t*k*d) dispatch transients (the [kT, D]
    sorted buffer), and NONE of the old no_drop path's O(E*k*t*d)
    worst-case capacity buffers ([E, kT, D] / [E, kT, F] — ~2 GiB a layer
    in bf16). Abstract lowering only: no weights materialize."""
    from distributed_training_guide_tpu.models import moe

    cfg = moe.PRESETS["qwen1.5-moe-a2.7b"]
    T = 2048
    params = jax.eval_shape(lambda: moe.init(cfg, jax.random.key(0)))
    cache = jax.eval_shape(lambda: moe.init_cache(cfg, 1, T))
    ids = jax.ShapeDtypeStruct((1, T), jnp.int32)
    txt = jax.jit(lambda p, i, c: moe.prefill(cfg, p, i, c)).lower(
        params, ids, cache).as_text()
    kT = cfg.experts_per_token * T
    E, D, F = cfg.num_experts, cfg.hidden_size, cfg.intermediate_size
    assert hlo_util.has_shape_run(txt, (kT, D)), \
        "ragged [kT, D] sorted buffer missing"
    for dense_shape in ((E, kT, D), (E, kT, F), (kT, E)):
        assert not hlo_util.has_shape_run(txt, dense_shape), (
            f"O(E*k*t) dispatch transient {list(dense_shape)} in decode "
            f"lowering")


@pytest.mark.grouped
def test_moe_dispatch_validation():
    """Unknown moe_dispatch values fail loudly at Trainer build (and at
    forward time for direct model users)."""
    bundle = get_model("moe-debug", dtype=jnp.float32, moe_dispatch="sparse")
    with pytest.raises(ValueError, match="unknown moe_dispatch"):
        Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), donate=False)
    params = bundle.init(bundle.config, jax.random.key(0))
    ids = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="unknown moe_dispatch"):
        bundle.apply_with_aux(bundle.config, params, ids)


def test_moe_per_layer_windows_flash_matches_xla():
    """MoE families thread the per-layer window column through their scan
    too (VERDICT #8b): moe-debug with an alternating sliding/full pattern —
    fwd+grad parity between the flash (interpret) and xla paths, and the
    band must genuinely bind (different loss than unwindowed). seq 32 >
    window 8, fp32."""
    bundle = get_model("moe-debug", dtype=jnp.float32, layer_windows=(8, 0))
    assert bundle.config.layer_windows == (8, 0)
    params = bundle.init(bundle.config, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 32), 0,
                             bundle.config.vocab_size)

    def loss_fn(p, impl):
        lg, ax = bundle.apply_with_aux(bundle.config, p, ids, attn_impl=impl)
        return causal_lm_loss(lg, ids) + 0.01 * ax

    lx, gx = jax.value_and_grad(loss_fn)(params, "xla")
    lf, gf = jax.value_and_grad(loss_fn)(params, "flash")
    np.testing.assert_allclose(float(lf), float(lx), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gx)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    # the window binds: an unwindowed twin's logits must differ
    full = get_model("moe-debug", dtype=jnp.float32)
    lg_win, _ = bundle.apply_with_aux(bundle.config, params, ids)
    lg_full, _ = full.apply_with_aux(full.config, params, ids)
    assert float(jnp.max(jnp.abs(lg_win - lg_full))) > 1e-4
