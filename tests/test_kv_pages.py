"""Paged-KV primitives: allocator invariants, the gather-based attend vs
the contiguous-cache reference, and the byte pricing the preflight report
uses. Pure serve/kv_pages.py coverage — the engine-level behavior
(scheduling, parity, backpressure) lives in test_serve.py."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.ops.attention import multihead_attention
from distributed_training_guide_tpu.serve.kv_pages import (
    TRASH_PAGE, PagePool, commit_prefill, kv_page_bytes, paged_attend,
    pages_for_tokens)

pytestmark = pytest.mark.serve


# ---- allocator --------------------------------------------------------------

def test_pool_never_hands_out_the_trash_page():
    pool = PagePool(n_pages=8, page_size=4)
    got = pool.alloc(pool.capacity)
    assert got is not None and TRASH_PAGE not in got
    assert sorted(got) == list(range(1, 8))


def test_pool_all_or_nothing_and_backpressure():
    pool = PagePool(n_pages=6, page_size=4)   # 5 usable
    a = pool.alloc(3)
    assert len(a) == 3 and pool.n_free == 2
    assert pool.alloc(3) is None              # refuse, don't partially grant
    assert pool.n_free == 2                   # refusal left the pool intact
    pool.free(a)
    assert pool.alloc(5) is not None


def test_pool_free_validates():
    pool = PagePool(n_pages=6, page_size=4)
    pages = pool.alloc(2)
    pool.free(pages)
    with pytest.raises(ValueError, match="double free"):
        pool.free([pages[0]])
    with pytest.raises(ValueError, match="invalid page"):
        pool.free([TRASH_PAGE])
    with pytest.raises(ValueError, match="invalid page"):
        pool.free([99])


def test_pool_errors_carry_holder_context():
    """The localization satellite: validation errors name the page's
    refcount / free-list state and the pool's pressure — a bare id out
    of a thousand-iteration chaos trace was needlessly slow to chase."""
    pool = PagePool(n_pages=6, page_size=4)
    [p] = pool.alloc(1)
    pool.free([p])
    with pytest.raises(ValueError,
                       match=rf"page {p}: refcount 0, free-listed"):
        pool.free([p])
    with pytest.raises(ValueError, match=r"pool \d+/5 free"):
        pool.share([p])
    with pytest.raises(ValueError, match="out of range"):
        pool.free([99])
    with pytest.raises(ValueError, match="trash page"):
        pool.free([TRASH_PAGE])
    # a batch with duplicates reports how often the batch releases it
    [q] = pool.alloc(1)
    with pytest.raises(ValueError, match="releases it 2x"):
        pool.free([q, q])
    assert pool.refcount(q) == 1              # validated before mutation


def test_pool_refcount_lifecycle():
    """share/free reference counting: a page re-enters the free list at
    the LAST release exactly, sharing a dead page is refused, and a batch
    releasing more references than exist fails without mutating."""
    pool = PagePool(n_pages=6, page_size=4)
    [p] = pool.alloc(1)
    assert pool.refcount(p) == 1
    pool.share([p])
    pool.share([p])
    assert pool.refcount(p) == 3
    pool.free([p])
    pool.free([p])
    assert pool.refcount(p) == 1 and pool.n_free == 4   # still held
    pool.free([p])
    assert pool.refcount(p) == 0 and pool.n_free == 5   # last release
    with pytest.raises(ValueError, match="double free"):
        pool.free([p])
    with pytest.raises(ValueError, match="unallocated"):
        pool.share([p])
    # duplicate ids past the live count fail BEFORE any mutation
    [q] = pool.alloc(1)
    with pytest.raises(ValueError, match="double free"):
        pool.free([q, q])
    assert pool.refcount(q) == 1


def test_pool_free_list_is_lifo_with_set_membership():
    """The satellite fix: membership checks moved to a set, but reissue
    order stays LIFO (recently freed pages come back first, keeping the
    hot working set compact)."""
    pool = PagePool(n_pages=10, page_size=4)
    a = pool.alloc(4)
    pool.free(a)
    assert pool.alloc(4) == a                   # LIFO reissue
    assert pool._free_set == set(pool._free)    # set mirrors the list


def test_pages_for_tokens_rounds_up():
    assert pages_for_tokens(1, 16) == 1
    assert pages_for_tokens(16, 16) == 1
    assert pages_for_tokens(17, 16) == 2


def test_kv_page_bytes_formula():
    from distributed_training_guide_tpu.models import get_model

    cfg = get_model("llama-debug", dtype=jnp.float32).config
    # pages x layers x 2 (k,v) x page_size x kv_heads x head_dim x 4 bytes
    expect = 3 * cfg.num_layers * 2 * 16 * cfg.num_kv_heads * cfg.head_size * 4
    assert kv_page_bytes(cfg, page_size=16, n_pages=3) == expect


# ---- device-side ops --------------------------------------------------------

def _contiguous_reference(q, k_ctx, v_ctx, length):
    """Attend q over the first ``length`` contiguous positions (the
    dense-cache decode math)."""
    t = k_ctx.shape[0]
    kv_pos = jnp.arange(t)[None, :]
    return multihead_attention(
        q[None], k_ctx[None], v_ctx[None], causal=True,
        positions=jnp.asarray([[length]]), kv_positions=kv_pos,
        impl="xla", standard_layout=False)[0]


def test_paged_attend_matches_contiguous_cache():
    """Scatter a known contiguous k/v history into shuffled physical pages,
    then paged_attend must equal attention over the contiguous buffer —
    per slot, at different lengths, including the freshly written token."""
    page, n_pages, hkv, hq, d = 4, 16, 2, 4, 8
    s, m = 3, 4                               # 3 slots, 4 logical pages each
    rng = np.random.default_rng(0)
    lengths = np.array([5, 0, 11], np.int32)  # new token positions per slot
    # physical layout: shuffled non-overlapping pages per slot
    phys = rng.permutation(np.arange(1, n_pages))
    tables = np.zeros((s, m), np.int32)
    for i in range(s):
        tables[i] = phys[i * m:(i + 1) * m]

    ctx = rng.standard_normal((s, m * page, hkv, d)).astype(np.float32)
    k_pages = np.zeros((n_pages, page, hkv, d), np.float32)
    v_pages = np.zeros((n_pages, page, hkv, d), np.float32)
    vctx = rng.standard_normal((s, m * page, hkv, d)).astype(np.float32)
    for i in range(s):
        for t in range(int(lengths[i])):      # history: tokens 0..len-1
            k_pages[tables[i, t // page], t % page] = ctx[i, t]
            v_pages[tables[i, t // page], t % page] = vctx[i, t]

    q = rng.standard_normal((s, 1, hq, d)).astype(np.float32)
    k_new = rng.standard_normal((s, 1, hkv, d)).astype(np.float32)
    v_new = rng.standard_normal((s, 1, hkv, d)).astype(np.float32)

    out, (nkp, nvp) = jax.jit(paged_attend)(
        q, k_new, v_new, jnp.asarray(k_pages), jnp.asarray(v_pages),
        jnp.asarray(tables), jnp.asarray(lengths))

    for i in range(s):
        n = int(lengths[i])
        k_ctx = np.concatenate([ctx[i, :n], k_new[i]], axis=0)
        v_ctx = np.concatenate([vctx[i, :n], v_new[i]], axis=0)
        ref = _contiguous_reference(jnp.asarray(q[i]), jnp.asarray(k_ctx),
                                    jnp.asarray(v_ctx), n)
        np.testing.assert_allclose(np.asarray(out[i]), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        # the write landed at the slot's own (page, offset)
        np.testing.assert_array_equal(
            np.asarray(nkp[tables[i, n // page], n % page]), k_new[i, 0])


def test_paged_attend_idle_slot_writes_to_trash():
    """A zeroed table row + length 0 (an idle lane of the fixed decode
    batch) must scatter into page 0 only — allocated pages stay bitwise
    untouched."""
    page, n_pages, h, d = 4, 6, 2, 8
    k_pages = jnp.asarray(
        np.random.default_rng(1).standard_normal((n_pages, page, h, d)),
        jnp.float32)
    v_pages = k_pages + 1
    tables = jnp.zeros((1, 2), jnp.int32)
    q = jnp.ones((1, 1, h, d), jnp.float32)
    kv = jnp.ones((1, 1, h, d), jnp.float32)
    _, (nkp, nvp) = paged_attend(q, kv, kv, k_pages, v_pages, tables,
                                 jnp.zeros(1, jnp.int32))
    np.testing.assert_array_equal(np.asarray(nkp[1:]),
                                  np.asarray(k_pages[1:]))
    np.testing.assert_array_equal(np.asarray(nvp[1:]),
                                  np.asarray(v_pages[1:]))
    np.testing.assert_array_equal(np.asarray(nkp[TRASH_PAGE, 0]),
                                  np.ones((h, d), np.float32))


def test_paged_attend_multi_token_chunk_matches_contiguous():
    """The chunked-prefill contract: T new tokens scatter at positions
    lengths..lengths+T-1 and attend over history + themselves; the padded
    tail (past n_valid) scatters to the trash page only."""
    page, n_pages, hkv, hq, d = 4, 12, 2, 4, 8
    m, t, hist = 4, 6, 5                     # 5 cached tokens, 6-token chunk
    rng = np.random.default_rng(7)
    tables = np.asarray([[3, 7, 2, 9]], np.int32)
    ctx = rng.standard_normal((hist + t, hkv, d)).astype(np.float32)
    vctx = rng.standard_normal((hist + t, hkv, d)).astype(np.float32)
    k_pages = np.zeros((n_pages, page, hkv, d), np.float32)
    v_pages = np.zeros((n_pages, page, hkv, d), np.float32)
    for j in range(hist):
        k_pages[tables[0, j // page], j % page] = ctx[j]
        v_pages[tables[0, j // page], j % page] = vctx[j]

    q = rng.standard_normal((1, t, hq, d)).astype(np.float32)
    real = 4                                  # final-chunk padding: 2 pad
    out, (nkp, nvp) = jax.jit(paged_attend, static_argnames=())(
        q, ctx[None, hist:], vctx[None, hist:], jnp.asarray(k_pages),
        jnp.asarray(v_pages), jnp.asarray(tables),
        jnp.asarray([hist], jnp.int32), n_valid=jnp.asarray([real]))
    nkp = np.asarray(nkp)

    # real chunk rows equal attention over the contiguous history + chunk
    kv_pos = jnp.arange(hist + t)[None]
    ref = multihead_attention(
        q, jnp.asarray(ctx)[None], jnp.asarray(vctx)[None], causal=True,
        positions=jnp.asarray([[hist + j for j in range(t)]]),
        kv_positions=kv_pos, impl="xla", standard_layout=False)
    np.testing.assert_allclose(np.asarray(out)[0, :real],
                               np.asarray(ref)[0, :real],
                               rtol=1e-5, atol=1e-5)
    # real tokens landed at their logical (page, offset)
    for j in range(real):
        pos = hist + j
        np.testing.assert_array_equal(
            nkp[tables[0, pos // page], pos % page], ctx[pos])
    # pad tokens went to the trash page; the slot's own next positions are
    # untouched (still zero)
    for j in range(real, t):
        pos = hist + j
        assert not nkp[tables[0, pos // page], pos % page].any()


def test_copy_pages_forks_one_physical_page():
    """The CoW device copy: src duplicated into dst across all layers,
    everything else bitwise untouched."""
    from distributed_training_guide_tpu.serve.kv_pages import copy_pages

    rng = np.random.default_rng(8)
    kp = rng.standard_normal((2, 6, 4, 2, 8)).astype(np.float32)
    vp = rng.standard_normal((2, 6, 4, 2, 8)).astype(np.float32)
    nkp, nvp = jax.jit(copy_pages)(jnp.asarray(kp), jnp.asarray(vp),
                                   jnp.asarray(3), jnp.asarray(5))
    nkp, nvp = np.asarray(nkp), np.asarray(nvp)
    np.testing.assert_array_equal(nkp[:, 5], kp[:, 3])
    np.testing.assert_array_equal(nvp[:, 5], vp[:, 3])
    others = [0, 1, 2, 3, 4]
    np.testing.assert_array_equal(nkp[:, others], kp[:, others])
    np.testing.assert_array_equal(nvp[:, others], vp[:, others])


def test_commit_prefill_skips_shared_prefix_start():
    """``start`` routes already-resident (shared) positions to the trash
    page — a bucketed prefill over a shared prefix recomputes but never
    rewrites pages other sequences read through."""
    layers, page, n_pages, h, d = 2, 4, 8, 2, 4
    rng = np.random.default_rng(9)
    marker = rng.standard_normal((layers, page, h, d)).astype(np.float32)
    k_pages = np.zeros((layers, n_pages, page, h, d), np.float32)
    k_pages[:, 5] = marker                    # the shared page's content
    v_pages = np.zeros_like(k_pages)
    k_dense = rng.standard_normal((layers, 8, h, d)).astype(np.float32)
    v_dense = rng.standard_normal((layers, 8, h, d)).astype(np.float32)
    table_row = jnp.asarray([5, 3, 0, 0], jnp.int32)

    nkp, _ = jax.jit(commit_prefill)(
        jnp.asarray(k_pages), jnp.asarray(v_pages), jnp.asarray(k_dense),
        jnp.asarray(v_dense), table_row, jnp.asarray(6), jnp.asarray(4))
    nkp = np.asarray(nkp)
    np.testing.assert_array_equal(nkp[:, 5], marker)        # untouched
    for t in (4, 5):                                        # committed
        np.testing.assert_array_equal(nkp[:, 3, t % page], k_dense[:, t])


def test_commit_prefill_routes_pad_tail_to_trash():
    """Bucketed prefill: real tokens land in the slot's pages in logical
    order, the padded tail goes to page 0, other pages untouched."""
    layers, page, n_pages, h, d = 2, 4, 8, 2, 4
    bucket, n_tokens = 8, 6
    rng = np.random.default_rng(2)
    k_pages = jnp.zeros((layers, n_pages, page, h, d), jnp.float32)
    v_pages = jnp.zeros_like(k_pages)
    k_dense = rng.standard_normal((layers, bucket, h, d)).astype(np.float32)
    v_dense = rng.standard_normal((layers, bucket, h, d)).astype(np.float32)
    table_row = jnp.asarray([5, 3, 0, 0], jnp.int32)

    nkp, nvp = jax.jit(commit_prefill)(
        k_pages, v_pages, jnp.asarray(k_dense), jnp.asarray(v_dense),
        table_row, jnp.asarray(n_tokens))
    nkp, nvp = np.asarray(nkp), np.asarray(nvp)
    for t in range(n_tokens):
        pg = [5, 3][t // page]
        np.testing.assert_array_equal(nkp[:, pg, t % page], k_dense[:, t])
        np.testing.assert_array_equal(nvp[:, pg, t % page], v_dense[:, t])
    untouched = [p for p in range(1, n_pages) if p not in (5, 3)]
    assert not nkp[:, untouched].any() and not nvp[:, untouched].any()
