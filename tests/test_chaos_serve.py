"""Serve-plane chaos drills (ISSUE 12 acceptance): the utils/faults.py
env knobs drive the REAL failure paths — a cross-host handoff torn or
timed out mid-flight, a replica SIGKILL mid-decode, a slow-heartbeat
wedge — and after every drill the invariants are pinned PER ITERATION:

- page refcounts equal the number of holders on every surviving engine
  (in-transit handoff records counted on whichever side still holds
  pages);
- free + held + cached == capacity on every surviving pool;
- every submitted request completes (possibly via drop-requeue or fence
  resubmission) token-identical to its batch-1 reference, or as a
  strict prefix with a structured finish_reason — never silently wrong,
  never a leaked or double-issued page.

These are executable documentation for the failure-drills table in
``diagnosing-errors/README.md``; the same switches run against a real
fleet.
"""
import dataclasses

import jax
import jax.numpy as jnp
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.serve import Request, ServeEngine
from distributed_training_guide_tpu.serve.api import generate_many
from distributed_training_guide_tpu.serve.disagg import DisaggEngine
from distributed_training_guide_tpu.serve.router import Replica, Router
from distributed_training_guide_tpu.utils import faults

pytestmark = [pytest.mark.chaos, pytest.mark.serve]


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


def _fresh(req):
    return dataclasses.replace(req, request_id=None)


def _ref(bundle, params, req, **kw):
    eng = ServeEngine(bundle, params, n_slots=1, prefix_cache=False, **kw)
    return generate_many(eng, [_fresh(req)])[0]


# ---- audit helpers (the test_serve.py idiom, fleet-shaped) ------------------

def _slot_holders(sched):
    held: dict = {}
    for slot in sched.slots:
        if slot is None:
            continue
        assert 0 not in slot.pages, "trash page in a live table"
        for p in slot.pages:
            held[p] = held.get(p, 0) + 1
    return held


def _cache_refs(sched):
    refs: dict = {}
    if sched.cache is None:
        return refs
    stack = [sched.cache.root]
    while stack:
        node = stack.pop()
        for child in node.children.values():
            refs[child.page] = refs.get(child.page, 0) + 1
            stack.append(child)
    return refs


def _assert_pool(pool, holder_maps, where=""):
    held: dict = {}
    for m in holder_maps:
        for p, n in m.items():
            held[p] = held.get(p, 0) + n
    for p, n in held.items():
        assert pool.refcount(p) == n, \
            f"{where}: page {p}: {n} holders, refcount {pool.refcount(p)}"
    assert pool.n_free + len(held) == pool.capacity, \
        f"{where}: free {pool.n_free} + held {len(held)} " \
        f"!= capacity {pool.capacity}"


def _audit_disagg(eng):
    """Both pools of a disaggregated pair, in-transit records counted:
    same-host transit holds pool pages; cross-host transit is host/wire
    bytes (each pool audits independently)."""
    transit: dict = {}
    for h in eng.handoff.pending:
        for p in h.pages:
            transit[p] = transit.get(p, 0) + 1
    if eng.transport == "cross_host":
        _assert_pool(eng.pool, [_slot_holders(eng.prefill.sched),
                                _cache_refs(eng.prefill.sched)], "prefill")
        _assert_pool(eng.decode_pool,
                     [_slot_holders(eng.decode.sched), transit], "decode")
    else:
        _assert_pool(eng.pool, [_slot_holders(eng.prefill.sched),
                                _slot_holders(eng.decode.sched),
                                _cache_refs(eng.prefill.sched), transit],
                     "shared")


def _audit_monolith(eng):
    _assert_pool(eng.scheduler.pool,
                 [_slot_holders(eng.scheduler), _cache_refs(eng.scheduler)],
                 "monolith")


def _audit_engine(engine):
    if isinstance(engine, DisaggEngine):
        _audit_disagg(engine)
    else:
        _audit_monolith(engine)


# ---- handoff drills ---------------------------------------------------------

@pytest.mark.handoff
@pytest.mark.parametrize("knob,outcome", [
    (faults.ENV_HANDOFF_CRASH_XFER, "handoff_dropped_nak"),
    (faults.ENV_HANDOFF_TIMEOUT_XFER, "handoff_dropped_timeout"),
])
def test_handoff_fault_mid_flight_drops_frees_requeues(llama, monkeypatch,
                                                       knob, outcome):
    """A transfer torn (sender crash) or stalled (receiver wedge)
    mid-flight: the ONLY outcome is payload dropped + sender pages freed
    + request requeued at the prefill queue's head — the drilled request
    still completes token-identical, both pools audit clean after every
    iteration, and the wire counters name the failure."""
    bundle, params = llama
    monkeypatch.setenv(knob, "1")     # the 2nd transfer (0-indexed) fails
    eng = DisaggEngine(bundle, params, n_slots=2, n_prefill_slots=1,
                       page_size=4, max_len=16, transport="cross_host",
                       handoff_ack_timeout_s=0.3)
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=4,
                    temperature=0.8 if i % 2 else 0.0, seed=i)
            for i in range(4)]
    ids = [eng.submit(_fresh(r)) for r in reqs]
    done, it = {}, 0
    while eng.has_work:
        for res in eng.step():
            done[res.request_id] = res
        _audit_disagg(eng)
        it += 1
        assert it < 2000
    for rid, req in zip(ids, reqs):
        want = _ref(bundle, params, req, page_size=4, max_len=16)
        assert done[rid].token_ids == want.token_ids, f"seed={req.seed}"
    s = eng.stats()
    assert s["handoff_dropped"] == 1 and s[outcome] == 1
    assert s["handoff_requeued"] == 1
    assert s["handoff_delivered"] == len(reqs)       # the retry re-ships
    assert s["handoff_transfers"] == len(reqs) + 1
    assert eng.decode_pool.n_free == eng.decode_pool.capacity
    eng.close()


# ---- replica drills ---------------------------------------------------------

def _drive_fleet(router, reqs):
    ids = [router.submit(_fresh(r)) for r in reqs]
    done, it = {}, 0
    while router.has_work:
        for res in router.step():
            done[res.request_id] = res
        for replica in router.replicas.values():
            if replica.state == "live":
                _audit_engine(replica.engine)
        it += 1
        assert it < 5000
    return ids, done


@pytest.mark.router
def test_replica_sigkill_mid_decode_drill(llama, monkeypatch):
    """DTG_FAULT_REPLICA_KILL=<name>@<step>: the replica dies instantly
    mid-decode (no drain, no cleanup). The router fences it, resubmits
    its in-flight requests, and EVERY submitted request completes
    token-identical to batch-1; the survivor's pool audits clean after
    every iteration and balances post-mortem."""
    bundle, params = llama
    from distributed_training_guide_tpu.serve.router import local_fleet

    monkeypatch.setenv(faults.ENV_REPLICA_KILL, "r0@4")
    router = local_fleet(bundle, params, 2, n_slots=2, page_size=4,
                         max_len=32,
                         router_kw=dict(heartbeat_timeout_s=60.0))
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=10,
                    temperature=0.6 if i % 2 else 0.0, seed=i)
            for i in range(6)]
    ids, done = _drive_fleet(router, reqs)
    for rid, req in zip(ids, reqs):
        want = _ref(bundle, params, req, page_size=4, max_len=32)
        assert done[rid].token_ids == want.token_ids, f"seed={req.seed}"
    s = router.stats()
    assert s["fenced"] == 1 and s["resubmitted"] >= 1
    assert router.replicas["r0"].state == "fenced"
    surv = router.replicas["r1"].engine
    _audit_monolith(surv)
    assert surv.scheduler.pool.n_free \
        + surv.scheduler.cache_pages_held() == surv.scheduler.pool.capacity


@pytest.mark.router
def test_replica_wedge_drill_heartbeat_fences(llama, monkeypatch):
    """DTG_FAULT_REPLICA_WEDGE: the replica stays 'alive' but stops
    stepping and beating — only the heartbeat age catches it (real
    wall-clock here, 0.15s timeout). Its in-flight requests resubmit and
    complete identically; the wedged replica never double-issues (fenced
    replicas are never stepped again)."""
    bundle, params = llama
    from distributed_training_guide_tpu.serve.router import local_fleet

    import time

    monkeypatch.setenv(faults.ENV_REPLICA_WEDGE, "r1@3")
    router = local_fleet(bundle, params, 2, n_slots=2, page_size=4,
                         max_len=32,
                         router_kw=dict(heartbeat_timeout_s=0.15))
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=8, seed=i)
            for i in range(6)]
    ids = [router.submit(_fresh(r)) for r in reqs]
    done, it = {}, 0
    while router.has_work:
        for res in router.step():
            done[res.request_id] = res
        for replica in router.replicas.values():
            if replica.state == "live" and not replica.wedged:
                _audit_engine(replica.engine)
        # a wedged replica is caught by heartbeat AGE, which needs wall
        # time — idle router iterations are near-instant, so pace them
        time.sleep(0.002)
        it += 1
        assert it < 2000
    for rid, req in zip(ids, reqs):
        want = _ref(bundle, params, req, page_size=4, max_len=32)
        assert done[rid].token_ids == want.token_ids, f"seed={req.seed}"
    assert router.replicas["r1"].state == "fenced"
    assert router.replicas["r1"].wedged
    assert router.stats()["fenced"] == 1


@pytest.mark.router
@pytest.mark.handoff
def test_combined_drill_handoff_fault_plus_replica_kill(llama, monkeypatch):
    """The acceptance drill, all at once: a heterogeneous fleet (one
    cross-host disaggregated pair + one monolith) takes a handoff crash
    mid-flight AND a replica SIGKILL mid-decode in the same run. Every
    submitted request completes token-identical to batch-1 or as a
    strict prefix with a structured finish_reason; post-mortem audits on
    all surviving engines show refcount == holders and free + held +
    cached == capacity — no leaked or double-issued page."""
    bundle, params = llama
    monkeypatch.setenv(faults.ENV_HANDOFF_CRASH_XFER, "2")
    monkeypatch.setenv(faults.ENV_REPLICA_KILL, "mono@6")
    disagg = DisaggEngine(bundle, params, n_slots=2, n_prefill_slots=1,
                          page_size=4, max_len=32, transport="cross_host",
                          handoff_ack_timeout_s=0.3)
    mono = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32)
    router = Router([Replica("pair", disagg), Replica("mono", mono)],
                    heartbeat_timeout_s=60.0)
    reqs = [Request(prompt_ids=[3 + i, 17, 42, 9, 5][:2 + i % 3],
                    max_new_tokens=8,
                    temperature=0.7 if i % 3 == 1 else 0.0, seed=i)
            for i in range(8)]
    ids, done = _drive_fleet(router, reqs)
    structured = 0
    for rid, req in zip(ids, reqs):
        res = done[rid]
        want = _ref(bundle, params, req, page_size=4, max_len=32)
        if res.finish_reason in ("eos", "length"):
            assert res.token_ids == want.token_ids, f"seed={req.seed}"
        else:
            # the structured give-up: a strict prefix, never garbage
            assert res.finish_reason == "resubmit_exhausted"
            assert res.generated_ids == \
                want.generated_ids[:len(res.generated_ids)]
            structured += 1
    s = router.stats()
    assert s["fenced"] == 1
    assert router.replicas["mono"].state == "fenced"
    # the handoff fault fired iff the pair saw >= 3 transfers before the
    # workload drained; either way its counters must be self-consistent
    hs = disagg.handoff.stats
    assert hs["dropped"] == hs["requeued"]
    assert hs["transfers"] == hs["delivered"] + hs["dropped"]
    # post-mortem: every SURVIVING engine audits clean
    _audit_disagg(disagg)
    assert disagg.decode_pool.n_free == disagg.decode_pool.capacity
    assert disagg.pool.n_free \
        + disagg.prefill.sched.cache_pages_held() == disagg.pool.capacity
    assert structured == 0 or s["resubmit_exhausted"] == structured
    disagg.close()


# ---- control-plane drills (ISSUE 16 acceptance) -----------------------------

@pytest.mark.router
@pytest.mark.control
@pytest.mark.loadgen
def test_controller_scale_down_races_replica_kill_under_open_load(
        llama, monkeypatch):
    """The acceptance drill: open-loop Poisson arrivals drive a
    2-replica fleet under the SLO controller while chaos SIGKILLs r0
    mid-run — concurrent with whatever membership intent (drain/remove)
    the controller has in flight. Invariants: every admitted request
    finishes batch-1 token-identical or as a structured strict-prefix
    give-up (zero dropped tokens); the controller never leaves a route
    pointing at a fenced replica and never scales into one; live pools
    audit clean every iteration; the controller itself never raises,
    whichever way the drain-vs-kill race lands."""
    bundle, params = llama
    from distributed_training_guide_tpu.serve.controller import Controller
    from distributed_training_guide_tpu.serve.loadgen import poisson_arrivals
    from distributed_training_guide_tpu.serve.router import local_fleet
    from distributed_training_guide_tpu.serve.scheduler import RefusalError

    monkeypatch.setenv(faults.ENV_REPLICA_KILL, "r0@10")
    router = local_fleet(bundle, params, 2, n_slots=2, page_size=4,
                         max_len=32,
                         router_kw=dict(heartbeat_timeout_s=60.0))
    controller = Controller(router, hold_up=3, hold_down=2, cooldown_s=0.0,
                            min_replicas=1, max_replicas=2)
    # arrivals keyed to ROUTER STEPS (not wall time): deterministic, and
    # still open loop — submission never waits on a completion
    offsets = poisson_arrivals(1.5, 8.0, seed=0)
    arrival_step = [int(t * 3) for t in offsets]
    reqs = [Request(prompt_ids=[3 + i, 17, 42, 9][:2 + i % 3],
                    max_new_tokens=6,
                    temperature=0.7 if i % 2 else 0.0, seed=i)
            for i in range(len(offsets))]
    ids, done, refused = {}, {}, []
    it, next_i = 0, 0
    while next_i < len(reqs) or router.has_work:
        while next_i < len(reqs) and arrival_step[next_i] <= it:
            try:
                ids[next_i] = router.submit(_fresh(reqs[next_i]))
            except RefusalError as exc:
                refused.append((next_i, exc.reason))
            next_i += 1
        controller.step()               # must never raise, whatever chaos
        for res in router.step():
            done[res.request_id] = res
        for replica in router.replicas.values():
            if replica.state == "live":
                _audit_engine(replica.engine)
        it += 1
        assert it < 5000
    # zero dropped tokens: every ADMITTED request produced a result
    assert set(ids.values()) <= set(done), "an admitted request vanished"
    structured = 0
    for i, rid in ids.items():
        res = done[rid]
        want = _ref(bundle, params, reqs[i], page_size=4, max_len=32)
        if res.finish_reason in ("eos", "length"):
            assert res.token_ids == want.token_ids, f"seed={reqs[i].seed}"
        else:
            assert res.finish_reason == "resubmit_exhausted"
            assert res.generated_ids == \
                want.generated_ids[:len(res.generated_ids)]
            structured += 1
    # no route may point at a non-live replica once the dust settles
    for (name, _erid) in router._by_engine:
        assert router.replicas[name].state == "live"
    # the controller never scaled INTO a fenced replica: spawn targets
    # are fresh names, never a name the router fenced
    fenced = {n for n, r in router.replicas.items() if r.state == "fenced"}
    for action in controller.actions:
        if action["kind"] == "scale_up":
            assert action["target"] not in fenced
        if action["kind"] == "scale_down":
            kinds_before = [a["kind"] for a in controller.actions
                            if a["t"] <= action["t"]
                            and a.get("target") == action["target"]]
            assert "drain" in kinds_before, "remove without drain"
    # post-mortem: survivors audit clean
    for replica in router.replicas.values():
        if replica.state == "live":
            _audit_engine(replica.engine)
    assert controller.counters["observations"] > 0
    assert router.stats()["fenced"] <= 2


@pytest.mark.router
@pytest.mark.control
def test_replica_slow_gray_failure_is_never_fenced(llama, monkeypatch):
    """DTG_FAULT_REPLICA_SLOW=<name>@<delay>: the gray failure — r0 keeps
    stepping and beating but every iteration drags. Nothing may fence it
    (fencing a live replica double-risks its work); the workload still
    completes token-identical, and only load-aware signals see the
    drag."""
    bundle, params = llama
    from distributed_training_guide_tpu.serve.router import local_fleet

    monkeypatch.setenv(faults.ENV_REPLICA_SLOW, "r0@0.01")
    router = local_fleet(bundle, params, 2, n_slots=2, page_size=4,
                         max_len=32,
                         router_kw=dict(heartbeat_timeout_s=60.0))
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=4, seed=i)
            for i in range(4)]
    ids, done = _drive_fleet(router, reqs)
    for rid, req in zip(ids, reqs):
        want = _ref(bundle, params, req, page_size=4, max_len=32)
        assert done[rid].token_ids == want.token_ids, f"seed={req.seed}"
    assert router.replicas["r0"].state == "live", \
        "a slow replica is a capacity problem, not a health problem"
    assert router.stats()["fenced"] == 0


@pytest.mark.loadgen
def test_open_loop_harness_over_real_engine_accounts_every_request(llama):
    """run_open_loop over a REAL engine: wall-clock Poisson arrivals,
    no deadline (pure accounting pin) — offered == completed + refused +
    exhausted + missed, goodput positive, and the engine drains clean."""
    bundle, params = llama
    from distributed_training_guide_tpu.serve.loadgen import (
        build_schedule, default_scenarios, poisson_arrivals, run_open_loop)

    engine = ServeEngine(bundle, params, n_slots=2, page_size=4,
                         max_len=32, max_queue=16)
    vocab = int(bundle.config.vocab_size)
    scenarios = default_scenarios(max_len=32, page_size=4, vocab=vocab,
                                  deadline_s=None, seed=0)
    schedule = build_schedule(poisson_arrivals(5.0, 2.0, seed=0),
                              scenarios, vocab=vocab, seed=0)
    report = run_open_loop(engine, schedule, max_wall_s=60.0)
    assert not report.timed_out
    assert report.offered == len(schedule)
    assert report.completed + report.refused + report.deadline_missed \
        + report.resubmit_exhausted + report.other_failed == report.offered
    assert report.completed > 0 and report.goodput_rps > 0
    assert report.ttft_p99_s >= report.ttft_p50_s >= 0
    assert not engine.has_work
    _audit_monolith(engine)
