"""Chunked cross-entropy must match the full-logits loss in value and grads."""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.models import llama as llama_mod
from distributed_training_guide_tpu.ops.cross_entropy import (
    IGNORE_INDEX, causal_lm_loss, chunked_causal_lm_loss)
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine


def test_chunked_matches_full_including_padding():
    rng = jax.random.key(0)
    b, s, e, v = 2, 13, 16, 32  # s-1 = 12, not divisible by 5 -> padding path
    hidden = jax.random.normal(rng, (b, s, e), jnp.float32)
    w = jax.random.normal(jax.random.key(1), (e, v), jnp.float32)
    labels = jax.random.randint(jax.random.key(2), (b, s), 0, v)
    labels = labels.at[0, 3].set(IGNORE_INDEX)

    full = causal_lm_loss(jnp.einsum("bse,ev->bsv", hidden, w), labels)
    for chunks in (1, 3, 5):
        ck = chunked_causal_lm_loss(hidden, w, labels, num_chunks=chunks)
        np.testing.assert_allclose(float(ck), float(full), rtol=1e-6)

    g_full = jax.grad(lambda h, w: causal_lm_loss(
        jnp.einsum("bse,ev->bsv", h, w), labels), argnums=(0, 1))(hidden, w)
    g_ck = jax.grad(lambda h, w: chunked_causal_lm_loss(
        h, w, labels, num_chunks=3), argnums=(0, 1))(hidden, w)
    for a, c in zip(g_full, g_ck):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), rtol=1e-5, atol=1e-6)


def test_trainer_loss_chunks_matches(eight_devices):
    bundle = get_model("llama-debug", dtype=jnp.float32)
    opt = adamw_cosine(1e-3)
    ids = np.random.RandomState(0).randint(0, 512, (8, 33))

    def run(loss_chunks):
        t = Trainer(bundle=bundle, optimizer=opt,
                    plan=make_plan("fsdp", make_mesh(fsdp=8)),
                    loss_chunks=loss_chunks, donate=False)
        state = t.init_state(0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        state, m = t.step_fn(state, batch)
        return float(m["loss"]), state

    loss_full, s1 = run(0)
    loss_chunked, s2 = run(4)
    np.testing.assert_allclose(loss_chunked, loss_full, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s2.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5)


def test_trainer_loss_chunks_matches_moe(eight_devices):
    """Chunked CE composes with the MoE aux-loss path (router aux + dropped
    metric must survive the return_hidden forward)."""
    bundle = get_model("moe-debug", dtype=jnp.float32)
    opt = adamw_cosine(1e-3)
    ids = np.random.RandomState(1).randint(0, 512, (8, 33))

    def run(loss_chunks):
        t = Trainer(bundle=bundle, optimizer=opt,
                    plan=make_plan("ep", make_mesh(ep=4)),
                    loss_chunks=loss_chunks, donate=False)
        state = t.init_state(0)
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        state, m = t.step_fn(state, batch)
        assert "moe_dropped_frac" in m
        return float(m["loss"]), state

    loss_full, s1 = run(0)
    loss_chunked, s2 = run(4)
    np.testing.assert_allclose(loss_chunked, loss_full, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s2.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5)
