"""wandb integration smoke tests (reference C27) with a stubbed offline wandb.

wandb is a soft dependency and absent from the hermetic test image, so these
tests inject a minimal stand-in that mimics ``WANDB_MODE=offline`` behavior
(a run directory on disk, no network) and assert the live integration paths:
flag wiring through ``run_training``, the process-0 pattern, per-host groups,
and run-id persistence for resume.
"""
import sys
import types

import numpy as np
import pytest

from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train.cli import get_parser, run_training


class FakeRun:
    def __init__(self, kwargs):
        self.kwargs = kwargs


def make_fake_wandb(tmp_path):
    mod = types.ModuleType("wandb")
    mod.logged = []
    mod.inits = []
    mod.finished = 0

    def init(**kwargs):
        mod.inits.append(kwargs)
        run_dir = tmp_path / "wandb" / f"offline-run-{len(mod.inits)}"
        run_dir.mkdir(parents=True, exist_ok=True)
        return FakeRun(kwargs)

    def log(info, step=None):
        mod.logged.append((dict(info), step))

    def finish():
        mod.finished += 1

    mod.init = init
    mod.log = log
    mod.finish = finish
    mod.util = types.SimpleNamespace(generate_id=lambda: "fakeid01")
    return mod


@pytest.fixture
def fake_wandb(tmp_path, monkeypatch):
    mod = make_fake_wandb(tmp_path)
    monkeypatch.setitem(sys.modules, "wandb", mod)
    return mod


def make_args(tmp_path, **over):
    args = get_parser().parse_args(["-m", "llama-debug"])
    args.dataset_name = "synthetic:60000"
    args.seq_length = 64
    args.batch_size = 1
    args.num_epochs = 1
    args.log_freq = 2
    args.max_steps = 4
    args.save_dir = str(tmp_path)
    for k, v in over.items():
        setattr(args, k, v)
    return args


def test_wandb_logs_info_dict(tmp_path, fake_wandb, eight_devices):
    args = make_args(tmp_path, wandb=True)
    out = run_training(args, lambda: make_plan("ddp", make_mesh()))
    assert out["host_state"]["global_step"] == 4
    assert len(fake_wandb.inits) == 1
    assert fake_wandb.inits[0]["project"] == "distributed-training-guide-tpu"
    assert len(fake_wandb.logged) == 2  # log_freq=2 over 4 steps
    info, step = fake_wandb.logged[-1]
    assert np.isfinite(info["running_loss"]) and step == 4
    assert fake_wandb.finished == 1
    assert any((tmp_path / "wandb").iterdir())  # offline run dir exists


def test_wandb_run_id_persists_for_resume(tmp_path, fake_wandb, eight_devices):
    args = make_args(tmp_path, wandb=True, experiment_name="exp", ckpt_freq=2,
                     max_steps=2)
    run_training(args, lambda: make_plan("ddp", make_mesh()))
    id_file = tmp_path / "exp" / "wandb_id.txt"
    assert id_file.read_text() == "fakeid01"
    assert fake_wandb.inits[0]["id"] == "fakeid01"
    assert fake_wandb.inits[0]["resume"] == "allow"
    # a restarted job re-uses the stored id (same curve)
    args2 = make_args(tmp_path, wandb=True, experiment_name="exp", ckpt_freq=2,
                      max_steps=4)
    run_training(args2, lambda: make_plan("ddp", make_mesh()))
    assert fake_wandb.inits[1]["id"] == "fakeid01"


def test_wandb_per_host_pattern(tmp_path, fake_wandb, eight_devices):
    args = make_args(tmp_path, wandb=True, wandb_per_host=True,
                     experiment_name="grp")
    run_training(args, lambda: make_plan("ddp", make_mesh()))
    assert fake_wandb.inits[0]["group"] == "grp"
    assert fake_wandb.inits[0]["name"] == "proc-0"


def test_no_wandb_is_noop(tmp_path, eight_devices):
    # without --wandb (and with wandb uninstalled) training runs unchanged
    args = make_args(tmp_path)
    out = run_training(args, lambda: make_plan("ddp", make_mesh()))
    assert out["host_state"]["global_step"] == 4
