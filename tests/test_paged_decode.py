"""Paged flash kernel correctness: interpret-mode parity against the XLA
gather reference across the serving feature grid (GQA, sliding window —
static and traced, score scale, softcap, shuffled physical page layouts,
page-boundary lengths) at EVERY query-tile size — T=1 decode, T>1
verify/chunk tiles with ``n_valid`` pad tails, int8 and bf16 pools —
plus the engine-level pins: flash and xla attends produce identical
tokens, and the flash decode/chunk/verify programs' HLO carries no
[S, M*page, Hkv, D] gathered view (the xla programs show it)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.ops.attention import multihead_attention
from distributed_training_guide_tpu.utils import hlo as hlo_util
from distributed_training_guide_tpu.ops.paged_decode import (
    paged_decode_eligible, paged_flash_attend, paged_flash_decode)
from distributed_training_guide_tpu.serve.kv_pages import (paged_attend,
                                                           quantize_kv)

pytestmark = [pytest.mark.serve, pytest.mark.flash_decode]


def _random_paged_state(rng, *, s, m, page, n_pages, hkv, d):
    """Shuffled non-overlapping physical pages per slot + dense mirrors."""
    phys = rng.permutation(np.arange(1, n_pages))
    tables = np.zeros((s, m), np.int32)
    for i in range(s):
        tables[i] = phys[i * m:(i + 1) * m]
    k_pages = rng.standard_normal((n_pages, page, hkv, d)).astype(np.float32)
    v_pages = rng.standard_normal((n_pages, page, hkv, d)).astype(np.float32)
    return tables, k_pages, v_pages


def _gather_reference(q, k_pages, v_pages, tables, lengths, *, window=None,
                      scale=None, softcap=None):
    """The XLA logical-view attend (what serve ran before the kernel)."""
    s, m = tables.shape
    page = k_pages.shape[1]
    kg = k_pages[tables].reshape(s, m * page, *k_pages.shape[2:])
    vg = v_pages[tables].reshape(s, m * page, *v_pages.shape[2:])
    kv_pos = jnp.broadcast_to(jnp.arange(m * page)[None], (s, m * page))
    return multihead_attention(
        jnp.asarray(q)[:, None], jnp.asarray(kg), jnp.asarray(vg),
        causal=True, positions=jnp.asarray(lengths)[:, None],
        kv_positions=kv_pos, impl="xla", standard_layout=False,
        window=window, scale=scale, logit_softcap=softcap)[:, 0]


FEATURE_GRID = [
    dict(),                                          # plain causal
    dict(window=4),                                  # SWA inside one page
    dict(window=9),                                  # SWA across pages
    dict(scale=0.3),                                 # Gemma-2 score scale
    dict(softcap=20.0),                              # Gemma-2 softcap
    dict(window=8, scale=0.25, softcap=50.0),        # full Gemma-2 decode
]


@pytest.mark.parametrize("hq,hkv", [(4, 2), (2, 2), (8, 1)])
@pytest.mark.parametrize("kw", FEATURE_GRID,
                         ids=lambda kw: "-".join(kw) or "causal")
def test_kernel_matches_gather_reference(hq, hkv, kw):
    """Interpret-mode kernel vs the XLA gather path at <= 1e-5 over
    shuffled physical layouts and lengths hitting page starts/ends/zero."""
    rng = np.random.default_rng(0)
    s, m, page, n_pages, d = 4, 4, 4, 20, 8
    tables, k_pages, v_pages = _random_paged_state(
        rng, s=s, m=m, page=page, n_pages=n_pages, hkv=hkv, d=d)
    # positions: page boundary, zero, mid-page, last valid slot
    lengths = np.array([4, 0, 9, 15], np.int32)
    q = rng.standard_normal((s, hq, d)).astype(np.float32)

    out = paged_flash_decode(
        jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
        jnp.asarray(tables), jnp.asarray(lengths), interpret=True, **kw)
    ref = _gather_reference(q, jnp.asarray(k_pages), jnp.asarray(v_pages),
                            tables, lengths, **kw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_kernel_traced_window_matches_static():
    """A traced window (the per-layer Gemma-2 schedule rides lax.scan) must
    equal the static bake AND the reference; 2**30 encodes full causal."""
    rng = np.random.default_rng(1)
    s, m, page, n_pages, hq, hkv, d = 3, 4, 4, 16, 4, 2, 8
    tables, k_pages, v_pages = _random_paged_state(
        rng, s=s, m=m, page=page, n_pages=n_pages, hkv=hkv, d=d)
    lengths = np.array([5, 11, 14], np.int32)
    q = rng.standard_normal((s, hq, d)).astype(np.float32)
    args = (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(tables), jnp.asarray(lengths))

    traced = jax.jit(lambda w: paged_flash_decode(*args, window=w,
                                                  interpret=True))
    static = paged_flash_decode(*args, window=6, interpret=True)
    np.testing.assert_allclose(np.asarray(traced(jnp.asarray(6))),
                               np.asarray(static), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(traced(jnp.asarray(2 ** 30))),
        np.asarray(paged_flash_decode(*args, interpret=True)),
        rtol=1e-6, atol=1e-6)


def test_kernel_bf16_pages():
    """bf16 page pools (the serving dtype at scale): fp32 accumulation
    inside the kernel keeps parity with the gather reference at bf16
    tolerance."""
    rng = np.random.default_rng(2)
    s, m, page, n_pages, hq, hkv, d = 2, 2, 8, 8, 4, 2, 8
    tables, k_pages, v_pages = _random_paged_state(
        rng, s=s, m=m, page=page, n_pages=n_pages, hkv=hkv, d=d)
    kp = jnp.asarray(k_pages, jnp.bfloat16)
    vp = jnp.asarray(v_pages, jnp.bfloat16)
    lengths = np.array([3, 12], np.int32)
    q = jnp.asarray(rng.standard_normal((s, hq, d)), jnp.bfloat16)
    out = paged_flash_decode(q, kp, vp, jnp.asarray(tables),
                             jnp.asarray(lengths), interpret=True)
    ref = _gather_reference(q, kp, vp, tables, lengths)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=3e-2, atol=3e-2)


def test_kernel_validates_bad_static_window_and_tiles():
    rng = np.random.default_rng(3)
    tables, k_pages, v_pages = _random_paged_state(
        rng, s=1, m=2, page=4, n_pages=4, hkv=2, d=8)
    q = jnp.zeros((1, 4, 8), jnp.float32)
    with pytest.raises(ValueError, match="window"):
        paged_flash_decode(q, jnp.asarray(k_pages), jnp.asarray(v_pages),
                           jnp.asarray(tables), jnp.zeros(1, jnp.int32),
                           window=0, interpret=True)
    assert paged_decode_eligible(64, 8)
    assert not paged_decode_eligible(8, 8)      # head_dim not tiled
    assert not paged_decode_eligible(64, 4)     # page not tiled


def test_paged_attend_flash_matches_xla_dispatch():
    """The serve-layer dispatch: impl='flash' (interpret off-TPU) equals
    impl='xla' through the full paged_attend contract — scatter of the
    new token included."""
    rng = np.random.default_rng(4)
    s, m, page, n_pages, hq, hkv, d = 3, 4, 4, 16, 4, 2, 8
    tables, k_pages, v_pages = _random_paged_state(
        rng, s=s, m=m, page=page, n_pages=n_pages, hkv=hkv, d=d)
    lengths = jnp.asarray(np.array([5, 0, 11], np.int32))
    q = jnp.asarray(rng.standard_normal((s, 1, hq, d)).astype(np.float32))
    k_new = jnp.asarray(rng.standard_normal((s, 1, hkv, d)).astype(np.float32))
    v_new = jnp.asarray(rng.standard_normal((s, 1, hkv, d)).astype(np.float32))
    outs = {}
    for impl in ("flash", "xla"):
        attn, (kp, vp) = paged_attend(
            q, k_new, v_new, jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(tables), lengths, impl=impl, window=6, scale=0.3,
            softcap=30.0)
        outs[impl] = (np.asarray(attn), np.asarray(kp), np.asarray(vp))
    np.testing.assert_allclose(outs["flash"][0], outs["xla"][0],
                               rtol=1e-5, atol=1e-5)
    # the scatter is shared: pools must be BITWISE identical
    np.testing.assert_array_equal(outs["flash"][1], outs["xla"][1])
    np.testing.assert_array_equal(outs["flash"][2], outs["xla"][2])


# ---- engine-level pins ------------------------------------------------------

def test_engine_flash_decode_tokens_and_hlo_pin():
    """(a) an engine forced onto the kernel produces the same tokens as
    the gather engine; (b) the flash decode program's lowered HLO holds NO
    tensor shaped like the gathered [S, M*page, Hkv, D] view — the
    acceptance pin that the decode step stopped materializing it."""
    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.serve import Request, ServeEngine
    from distributed_training_guide_tpu.serve.api import generate_many

    bundle = get_model("llama-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(0))
    reqs = [Request(prompt_ids=[3, 17, 42], max_new_tokens=5, seed=1),
            Request(prompt_ids=[5, 6], max_new_tokens=6, seed=2)]
    res = {}
    engines = {}
    for impl in ("flash", "xla"):
        eng = ServeEngine(bundle, params, n_slots=2, page_size=4,
                          max_len=16, attend_impl=impl)
        res[impl] = generate_many(eng, reqs)
        engines[impl] = eng
    for a, b in zip(res["flash"], res["xla"]):
        assert a.token_ids == b.token_ids

    cfg = bundle.config
    for impl, expect_view in (("flash", False), ("xla", True)):
        eng = engines[impl]
        arr = eng.scheduler.decode_arrays()
        lowered = eng._decode_fn.lower(
            eng.params, eng.pages["k"], eng.pages["v"],
            jnp.asarray(arr["tokens"]), jnp.asarray(arr["lengths"]),
            jnp.asarray(arr["tables"]), jnp.asarray(arr["seeds"]),
            jnp.asarray(arr["temps"]), jnp.asarray(arr["top_ks"]),
            jnp.asarray(arr["top_ps"]), jnp.asarray(arr["actives"]))
        view = (eng.n_slots, eng.max_pages * eng.page_size,
                cfg.num_kv_heads, cfg.head_size)
        assert (hlo_util.has_shape_run(lowered.as_text(), view)
                == expect_view), (
            f"{impl}: gathered-view tensor "
            f"{'missing' if expect_view else 'present'} in the decode HLO")


# ---- the multi-token tile (block_q = T): verify / chunked prefill ----------

def _multitok_case(rng, *, s=3, t=4, m=4, page=4, n_pages=16, hq=4, hkv=2,
                   d=8):
    """Shuffled physical layout + a fresh [S, T] call's inputs: lengths
    hit zero / mid-page / a page crossing, and n_valid exercises full,
    partial, and single-token tails (the padded final chunk / short-draft
    shapes)."""
    tables, k_pages, v_pages = _random_paged_state(
        rng, s=s, m=m, page=page, n_pages=n_pages, hkv=hkv, d=d)
    lengths = np.array([0, 5, 9], np.int32)[:s]
    n_valid = np.array([t, max(1, t - 1), 1], np.int32)[:s]
    q = rng.standard_normal((s, t, hq, d)).astype(np.float32)
    k_new = rng.standard_normal((s, t, hkv, d)).astype(np.float32)
    v_new = rng.standard_normal((s, t, hkv, d)).astype(np.float32)
    return tables, k_pages, v_pages, lengths, n_valid, q, k_new, v_new


@pytest.mark.paged_multitok
@pytest.mark.parametrize("hq,hkv", [(4, 2), (2, 2), (8, 1)])
@pytest.mark.parametrize("kw", FEATURE_GRID,
                         ids=lambda kw: "-".join(kw) or "causal")
def test_multitoken_flash_matches_gather(hq, hkv, kw):
    """The [S, T] form through the full paged_attend contract — scatter
    of the T new tokens (n_valid tails trash-routed) then attend — must
    agree flash-vs-xla at <= 1e-5 on EVERY query row (pad rows read the
    same pool bytes under the same positional mask), with the shared
    scatter leaving BITWISE-identical pools. Windows at 4 and 9 fall
    inside / across the 4-token pages."""
    rng = np.random.default_rng(11)
    tables, k_pages, v_pages, lengths, n_valid, q, k_new, v_new = \
        _multitok_case(rng, hq=hq, hkv=hkv)
    outs = {}
    for impl in ("flash", "xla"):
        attn, (kp, vp) = paged_attend(
            jnp.asarray(q), jnp.asarray(k_new), jnp.asarray(v_new),
            jnp.asarray(k_pages), jnp.asarray(v_pages), jnp.asarray(tables),
            jnp.asarray(lengths), impl=impl,
            n_valid=jnp.asarray(n_valid), **kw)
        outs[impl] = (np.asarray(attn), np.asarray(kp), np.asarray(vp))
    np.testing.assert_allclose(outs["flash"][0], outs["xla"][0],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(outs["flash"][1], outs["xla"][1])
    np.testing.assert_array_equal(outs["flash"][2], outs["xla"][2])


@pytest.mark.paged_multitok
def test_multitoken_rank3_is_the_decode_form_bitwise():
    """T == 1 through the rank-4 tile IS the original decode kernel: the
    rank-3 entry point and a [S, 1, Hq, D] call must agree BITWISE (the
    row fold is a no-op transpose at T=1 — same layout, same op
    sequence)."""
    rng = np.random.default_rng(12)
    tables, k_pages, v_pages = _random_paged_state(
        rng, s=3, m=4, page=4, n_pages=16, hkv=2, d=8)
    lengths = np.array([3, 7, 12], np.int32)
    q = rng.standard_normal((3, 4, 8)).astype(np.float32)
    args = (jnp.asarray(k_pages), jnp.asarray(v_pages), jnp.asarray(tables),
            jnp.asarray(lengths))
    r3 = paged_flash_decode(jnp.asarray(q), *args, window=5, interpret=True)
    r4 = paged_flash_attend(jnp.asarray(q)[:, None], *args, window=5,
                            interpret=True)
    np.testing.assert_array_equal(np.asarray(r3), np.asarray(r4[:, 0]))


@pytest.mark.paged_multitok
def test_multitoken_traced_window_matches_static():
    """A traced window at T > 1 (the per-layer Gemma-2 schedule under the
    chunk/verify scan) must equal the static bake; 2**30 encodes full
    causal."""
    rng = np.random.default_rng(13)
    tables, k_pages, v_pages, lengths, _, q, _, _ = \
        _multitok_case(rng, hq=4, hkv=2)
    args = (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(tables), jnp.asarray(lengths))
    traced = jax.jit(lambda w: paged_flash_attend(*args, window=w,
                                                  interpret=True))
    static = paged_flash_attend(*args, window=6, interpret=True)
    np.testing.assert_allclose(np.asarray(traced(jnp.asarray(6))),
                               np.asarray(static), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(traced(jnp.asarray(2 ** 30))),
        np.asarray(paged_flash_attend(*args, interpret=True)),
        rtol=1e-6, atol=1e-6)


@pytest.mark.paged_multitok
def test_multitoken_bf16_pages():
    """bf16 pools at T > 1: fp32 accumulation inside the kernel keeps
    parity with the gather reference at bf16 tolerance."""
    rng = np.random.default_rng(14)
    tables, k_pages, v_pages, lengths, n_valid, q, k_new, v_new = \
        _multitok_case(rng, hq=4, hkv=2)
    outs = {}
    for impl in ("flash", "xla"):
        attn, _ = paged_attend(
            jnp.asarray(q, jnp.bfloat16), jnp.asarray(k_new, jnp.bfloat16),
            jnp.asarray(v_new, jnp.bfloat16),
            jnp.asarray(k_pages, jnp.bfloat16),
            jnp.asarray(v_pages, jnp.bfloat16), jnp.asarray(tables),
            jnp.asarray(lengths), impl=impl, n_valid=jnp.asarray(n_valid))
        assert attn.dtype == jnp.bfloat16
        outs[impl] = np.asarray(attn, np.float32)
    np.testing.assert_allclose(outs["flash"], outs["xla"],
                               rtol=3e-2, atol=3e-2)


@pytest.mark.paged_multitok
@pytest.mark.kvquant
def test_multitoken_int8_flash_matches_int8_gather():
    """The quantized pool at T > 1: in-kernel dequant (scale rows riding
    the block-table prefetch) vs the dequantized gather view on the SAME
    int8 pool — 1e-5 (both read identical payload+scale bytes), and the
    quantize-at-write scatter is bitwise shared (payload AND scales)."""
    rng = np.random.default_rng(15)
    tables, k_pages, v_pages, lengths, n_valid, q, k_new, v_new = \
        _multitok_case(rng, hq=4, hkv=2)
    kq = quantize_kv(jnp.asarray(k_pages))
    vq = quantize_kv(jnp.asarray(v_pages))
    outs = {}
    for impl in ("flash", "xla"):
        attn, (kp, vp) = paged_attend(
            jnp.asarray(q), jnp.asarray(k_new), jnp.asarray(v_new),
            kq, vq, jnp.asarray(tables), jnp.asarray(lengths), impl=impl,
            n_valid=jnp.asarray(n_valid), window=6, scale=0.3, softcap=30.0)
        outs[impl] = (np.asarray(attn), kp, vp)
    np.testing.assert_allclose(outs["flash"][0], outs["xla"][0],
                               rtol=1e-5, atol=1e-5)
    for leaf_f, leaf_x in zip(jax.tree.leaves(outs["flash"][1:]),
                              jax.tree.leaves(outs["xla"][1:])):
        np.testing.assert_array_equal(np.asarray(leaf_f), np.asarray(leaf_x))


# ---- engine-level multi-token pins ------------------------------------------

@pytest.mark.paged_multitok
def test_chunk_and_verify_programs_flash_hlo_pin():
    """THE acceptance pin for the kernel family: the chunk-prefill and
    spec-verify programs of a flash-family engine lower with NO gathered
    [S, M*page, Hkv, D] pool-shaped tensor, while the xla family's show
    it — chunked prefill and verify stopped paying the logical-view
    round-trip."""
    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.serve import ServeEngine

    bundle = get_model("llama-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(0))
    cfg = bundle.config
    for impl, expect_view in (("flash", False), ("xla", True)):
        eng = ServeEngine(bundle, params, n_slots=2, page_size=4,
                          max_len=16, attend_impl=impl, prefill_chunk=8,
                          speculate="ngram", spec_k=3)
        chunk = eng.programs.chunk_for(8).lower(
            eng.params, eng.pages["k"], eng.pages["v"],
            jnp.zeros((1, 8), jnp.int32), jnp.zeros((1,), jnp.int32),
            jnp.zeros((1, eng.max_pages), jnp.int32),
            jnp.asarray(7, jnp.int32), jnp.asarray([8], jnp.int32))
        view = (1, eng.max_pages * eng.page_size, cfg.num_kv_heads,
                cfg.head_size)
        assert (hlo_util.has_shape_run(chunk.as_text(), view)
                == expect_view), (
            f"{impl}: chunk program gathered view "
            f"{'missing' if expect_view else 'present'}")
        s = eng.n_slots
        verify = eng.programs.verify_for(4, greedy=True).lower(
            eng.params, eng.pages["k"], eng.pages["v"],
            jnp.zeros((s, 4), jnp.int32), jnp.zeros((s,), jnp.int32),
            jnp.zeros((s, eng.max_pages), jnp.int32),
            jnp.zeros((s,), jnp.int32), jnp.zeros((s,), jnp.float32),
            jnp.zeros((s,), jnp.int32), jnp.zeros((s,), jnp.float32),
            jnp.zeros((s,), jnp.bool_), jnp.zeros((s,), jnp.int32))
        view = (s, eng.max_pages * eng.page_size, cfg.num_kv_heads,
                cfg.head_size)
        assert (hlo_util.has_shape_run(verify.as_text(), view)
                == expect_view), (
            f"{impl}: verify program gathered view "
            f"{'missing' if expect_view else 'present'}")


@pytest.mark.paged_multitok
def test_engine_chunked_prefill_flash_tokens_match_gather():
    """An engine whose chunk program runs the multi-token kernel produces
    the same tokens as the gather engine — prompt long enough for several
    chunks incl. a padded final one, co-resident decodes riding along."""
    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.serve import Request, ServeEngine
    from distributed_training_guide_tpu.serve.api import generate_many

    bundle = get_model("llama-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(0))
    prompt = [3 + (i % 40) for i in range(19)]
    reqs = [Request(prompt_ids=prompt + [50 + i], max_new_tokens=5,
                    temperature=0.0 if i % 2 == 0 else 0.8, seed=i)
            for i in range(3)]
    res = {}
    for impl in ("flash", "xla"):
        eng = ServeEngine(bundle, params, n_slots=3, page_size=4,
                          max_len=32, attend_impl=impl, prefill_chunk=8)
        res[impl] = generate_many(eng, reqs)
    for a, b in zip(res["flash"], res["xla"]):
        assert a.token_ids == b.token_ids


@pytest.mark.paged_multitok
@pytest.mark.spec
@pytest.mark.slow
def test_sharded_tp2_flash_multitok_grid(eight_devices):
    """The >=2-device multi-token grid (slow): tp=2 sharded pool on the
    FLASH family with chunked prefill AND speculation — the chunk and
    verify tiles run the kernel per chip inside the manual region, and
    tokens equal the plain unsharded engine's."""
    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan
    from distributed_training_guide_tpu.serve import Request, ServeEngine
    from distributed_training_guide_tpu.serve.api import generate_many

    bundle = get_model("llama-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(0))
    plan = make_plan("tp", make_mesh(tp=2, devices=eight_devices[:2]))
    rep = [9, 8, 7] * 4
    reqs = [Request(prompt_ids=rep + [40 + i], max_new_tokens=8,
                    temperature=0.0 if i % 2 == 0 else 0.9, seed=i)
            for i in range(4)]
    ref = generate_many(
        ServeEngine(bundle, params, n_slots=2, page_size=8, max_len=32),
        reqs)
    eng = ServeEngine(bundle, params, n_slots=2, page_size=8, max_len=32,
                      plan=plan, shard_kv=True, attend_impl="flash",
                      prefill_chunk=8, speculate="ngram", spec_k=3)
    got = generate_many(eng, reqs)
    for a, b in zip(got, ref):
        assert a.token_ids == b.token_ids
    assert eng.spec["tokens_drafted"] > 0, "the grid never speculated"
