"""Flash-decode kernel correctness: interpret-mode parity against the XLA
gather reference across the serving feature grid (GQA, sliding window —
static and traced, score scale, softcap, shuffled physical page layouts,
page-boundary lengths), plus the engine-level pins: flash and xla attends
produce identical tokens, and the flash decode program's HLO carries no
[S, M*page, Hkv, D] gathered view."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.ops.attention import multihead_attention
from distributed_training_guide_tpu.utils import hlo as hlo_util
from distributed_training_guide_tpu.ops.paged_decode import (
    paged_decode_eligible, paged_flash_decode)
from distributed_training_guide_tpu.serve.kv_pages import paged_attend

pytestmark = [pytest.mark.serve, pytest.mark.flash_decode]


def _random_paged_state(rng, *, s, m, page, n_pages, hkv, d):
    """Shuffled non-overlapping physical pages per slot + dense mirrors."""
    phys = rng.permutation(np.arange(1, n_pages))
    tables = np.zeros((s, m), np.int32)
    for i in range(s):
        tables[i] = phys[i * m:(i + 1) * m]
    k_pages = rng.standard_normal((n_pages, page, hkv, d)).astype(np.float32)
    v_pages = rng.standard_normal((n_pages, page, hkv, d)).astype(np.float32)
    return tables, k_pages, v_pages


def _gather_reference(q, k_pages, v_pages, tables, lengths, *, window=None,
                      scale=None, softcap=None):
    """The XLA logical-view attend (what serve ran before the kernel)."""
    s, m = tables.shape
    page = k_pages.shape[1]
    kg = k_pages[tables].reshape(s, m * page, *k_pages.shape[2:])
    vg = v_pages[tables].reshape(s, m * page, *v_pages.shape[2:])
    kv_pos = jnp.broadcast_to(jnp.arange(m * page)[None], (s, m * page))
    return multihead_attention(
        jnp.asarray(q)[:, None], jnp.asarray(kg), jnp.asarray(vg),
        causal=True, positions=jnp.asarray(lengths)[:, None],
        kv_positions=kv_pos, impl="xla", standard_layout=False,
        window=window, scale=scale, logit_softcap=softcap)[:, 0]


FEATURE_GRID = [
    dict(),                                          # plain causal
    dict(window=4),                                  # SWA inside one page
    dict(window=9),                                  # SWA across pages
    dict(scale=0.3),                                 # Gemma-2 score scale
    dict(softcap=20.0),                              # Gemma-2 softcap
    dict(window=8, scale=0.25, softcap=50.0),        # full Gemma-2 decode
]


@pytest.mark.parametrize("hq,hkv", [(4, 2), (2, 2), (8, 1)])
@pytest.mark.parametrize("kw", FEATURE_GRID,
                         ids=lambda kw: "-".join(kw) or "causal")
def test_kernel_matches_gather_reference(hq, hkv, kw):
    """Interpret-mode kernel vs the XLA gather path at <= 1e-5 over
    shuffled physical layouts and lengths hitting page starts/ends/zero."""
    rng = np.random.default_rng(0)
    s, m, page, n_pages, d = 4, 4, 4, 20, 8
    tables, k_pages, v_pages = _random_paged_state(
        rng, s=s, m=m, page=page, n_pages=n_pages, hkv=hkv, d=d)
    # positions: page boundary, zero, mid-page, last valid slot
    lengths = np.array([4, 0, 9, 15], np.int32)
    q = rng.standard_normal((s, hq, d)).astype(np.float32)

    out = paged_flash_decode(
        jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
        jnp.asarray(tables), jnp.asarray(lengths), interpret=True, **kw)
    ref = _gather_reference(q, jnp.asarray(k_pages), jnp.asarray(v_pages),
                            tables, lengths, **kw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_kernel_traced_window_matches_static():
    """A traced window (the per-layer Gemma-2 schedule rides lax.scan) must
    equal the static bake AND the reference; 2**30 encodes full causal."""
    rng = np.random.default_rng(1)
    s, m, page, n_pages, hq, hkv, d = 3, 4, 4, 16, 4, 2, 8
    tables, k_pages, v_pages = _random_paged_state(
        rng, s=s, m=m, page=page, n_pages=n_pages, hkv=hkv, d=d)
    lengths = np.array([5, 11, 14], np.int32)
    q = rng.standard_normal((s, hq, d)).astype(np.float32)
    args = (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(tables), jnp.asarray(lengths))

    traced = jax.jit(lambda w: paged_flash_decode(*args, window=w,
                                                  interpret=True))
    static = paged_flash_decode(*args, window=6, interpret=True)
    np.testing.assert_allclose(np.asarray(traced(jnp.asarray(6))),
                               np.asarray(static), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(traced(jnp.asarray(2 ** 30))),
        np.asarray(paged_flash_decode(*args, interpret=True)),
        rtol=1e-6, atol=1e-6)


def test_kernel_bf16_pages():
    """bf16 page pools (the serving dtype at scale): fp32 accumulation
    inside the kernel keeps parity with the gather reference at bf16
    tolerance."""
    rng = np.random.default_rng(2)
    s, m, page, n_pages, hq, hkv, d = 2, 2, 8, 8, 4, 2, 8
    tables, k_pages, v_pages = _random_paged_state(
        rng, s=s, m=m, page=page, n_pages=n_pages, hkv=hkv, d=d)
    kp = jnp.asarray(k_pages, jnp.bfloat16)
    vp = jnp.asarray(v_pages, jnp.bfloat16)
    lengths = np.array([3, 12], np.int32)
    q = jnp.asarray(rng.standard_normal((s, hq, d)), jnp.bfloat16)
    out = paged_flash_decode(q, kp, vp, jnp.asarray(tables),
                             jnp.asarray(lengths), interpret=True)
    ref = _gather_reference(q, kp, vp, tables, lengths)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=3e-2, atol=3e-2)


def test_kernel_validates_bad_static_window_and_tiles():
    rng = np.random.default_rng(3)
    tables, k_pages, v_pages = _random_paged_state(
        rng, s=1, m=2, page=4, n_pages=4, hkv=2, d=8)
    q = jnp.zeros((1, 4, 8), jnp.float32)
    with pytest.raises(ValueError, match="window"):
        paged_flash_decode(q, jnp.asarray(k_pages), jnp.asarray(v_pages),
                           jnp.asarray(tables), jnp.zeros(1, jnp.int32),
                           window=0, interpret=True)
    assert paged_decode_eligible(64, 8)
    assert not paged_decode_eligible(8, 8)      # head_dim not tiled
    assert not paged_decode_eligible(64, 4)     # page not tiled


def test_paged_attend_flash_matches_xla_dispatch():
    """The serve-layer dispatch: impl='flash' (interpret off-TPU) equals
    impl='xla' through the full paged_attend contract — scatter of the
    new token included."""
    rng = np.random.default_rng(4)
    s, m, page, n_pages, hq, hkv, d = 3, 4, 4, 16, 4, 2, 8
    tables, k_pages, v_pages = _random_paged_state(
        rng, s=s, m=m, page=page, n_pages=n_pages, hkv=hkv, d=d)
    lengths = jnp.asarray(np.array([5, 0, 11], np.int32))
    q = jnp.asarray(rng.standard_normal((s, 1, hq, d)).astype(np.float32))
    k_new = jnp.asarray(rng.standard_normal((s, 1, hkv, d)).astype(np.float32))
    v_new = jnp.asarray(rng.standard_normal((s, 1, hkv, d)).astype(np.float32))
    outs = {}
    for impl in ("flash", "xla"):
        attn, (kp, vp) = paged_attend(
            q, k_new, v_new, jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(tables), lengths, impl=impl, window=6, scale=0.3,
            softcap=30.0)
        outs[impl] = (np.asarray(attn), np.asarray(kp), np.asarray(vp))
    np.testing.assert_allclose(outs["flash"][0], outs["xla"][0],
                               rtol=1e-5, atol=1e-5)
    # the scatter is shared: pools must be BITWISE identical
    np.testing.assert_array_equal(outs["flash"][1], outs["xla"][1])
    np.testing.assert_array_equal(outs["flash"][2], outs["xla"][2])
    with pytest.raises(ValueError, match="single-token"):
        paged_attend(jnp.zeros((1, 2, hq, d)), jnp.zeros((1, 2, hkv, d)),
                     jnp.zeros((1, 2, hkv, d)), jnp.asarray(k_pages),
                     jnp.asarray(v_pages), jnp.asarray(tables[:1]),
                     lengths[:1], impl="flash")


# ---- engine-level pins ------------------------------------------------------

def test_engine_flash_decode_tokens_and_hlo_pin():
    """(a) an engine forced onto the kernel produces the same tokens as
    the gather engine; (b) the flash decode program's lowered HLO holds NO
    tensor shaped like the gathered [S, M*page, Hkv, D] view — the
    acceptance pin that the decode step stopped materializing it."""
    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.serve import Request, ServeEngine
    from distributed_training_guide_tpu.serve.api import generate_many

    bundle = get_model("llama-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(0))
    reqs = [Request(prompt_ids=[3, 17, 42], max_new_tokens=5, seed=1),
            Request(prompt_ids=[5, 6], max_new_tokens=6, seed=2)]
    res = {}
    engines = {}
    for impl in ("flash", "xla"):
        eng = ServeEngine(bundle, params, n_slots=2, page_size=4,
                          max_len=16, attend_impl=impl)
        res[impl] = generate_many(eng, reqs)
        engines[impl] = eng
    for a, b in zip(res["flash"], res["xla"]):
        assert a.token_ids == b.token_ids

    cfg = bundle.config
    for impl, expect_view in (("flash", False), ("xla", True)):
        eng = engines[impl]
        arr = eng.scheduler.decode_arrays()
        lowered = eng._decode_fn.lower(
            eng.params, eng.pages["k"], eng.pages["v"],
            jnp.asarray(arr["tokens"]), jnp.asarray(arr["lengths"]),
            jnp.asarray(arr["tables"]), jnp.asarray(arr["seeds"]),
            jnp.asarray(arr["temps"]), jnp.asarray(arr["top_ks"]),
            jnp.asarray(arr["top_ps"]), jnp.asarray(arr["actives"]))
        view = (eng.n_slots, eng.max_pages * eng.page_size,
                cfg.num_kv_heads, cfg.head_size)
        assert (hlo_util.has_shape_run(lowered.as_text(), view)
                == expect_view), (
            f"{impl}: gathered-view tensor "
            f"{'missing' if expect_view else 'present'} in the decode HLO")
