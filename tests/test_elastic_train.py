"""Elastic training runtime: mesh-resharding restore with loud
incompatible-layout failures (checkpoint/reshard.py), the world-agreement
protocol, and the supervisor's slice-loss renegotiation drill
(launch/elastic.py + launch/supervisor.py)."""
import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.checkpoint import (
    CheckpointIO, ReshardIncompatibleError, abstract_train_state,
    check_reshard_compatibility, describe_layout, mesh_descriptor,
    restore_train_state, stamp_host_state)
from distributed_training_guide_tpu.launch import elastic as el
from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine
from distributed_training_guide_tpu.train.precision import PrecisionPolicy
from distributed_training_guide_tpu.train.state import host_state_dict
from distributed_training_guide_tpu.utils import faults

pytestmark = pytest.mark.elastic

REPO = Path(__file__).parent.parent


# ---------------------------------------------------------------------------
# reshard compatibility (unit level: pure descriptors)
# ---------------------------------------------------------------------------

def _desc(**over):
    base = {"axes": {"fsdp": 8}, "device_count": 8, "strategy": "fsdp",
            "pp_stages": 1, "quant_block": None}
    base.update(over)
    return base


def test_compat_same_layout_is_silent():
    assert check_reshard_compatibility(_desc(), _desc()) is False


def test_compat_unstamped_checkpoint_allowed():
    assert check_reshard_compatibility(None, _desc()) is False
    assert check_reshard_compatibility({}, _desc()) is False


def test_compat_mesh_refactorization_is_a_reshard():
    target = _desc(axes={"fsdp": 4}, device_count=4)
    assert check_reshard_compatibility(_desc(), target) is True
    # tp <-> fsdp refactorization at the same device count too
    target = _desc(axes={"tp": 4, "fsdp": 2}, strategy="tp_fsdp")
    assert check_reshard_compatibility(_desc(), target) is True


def test_compat_pp_stage_split_fails_naming_both():
    saved = _desc(axes={"pp": 2, "fsdp": 4}, strategy="pp_fsdp",
                  pp_stages=2)
    with pytest.raises(ReshardIncompatibleError) as exc:
        check_reshard_compatibility(saved, _desc())
    msg = str(exc.value)
    assert "2-stage" in msg and "1 stage" in msg
    assert describe_layout(saved) in msg and describe_layout(_desc()) in msg
    assert exc.value.saved == saved and exc.value.target == _desc()


def test_compat_quant_block_tiling_fails_naming_both():
    saved = _desc(quant_block=64)
    target = _desc(quant_block=128)
    with pytest.raises(ReshardIncompatibleError) as exc:
        check_reshard_compatibility(saved, target)
    msg = str(exc.value)
    assert "block size 64" in msg and "block size 128" in msg
    # one side unquantized is NOT a tiling mismatch (the precision-policy
    # stamp owns that failure mode)
    assert check_reshard_compatibility(_desc(quant_block=None),
                                       target) is False


def test_mesh_descriptor_reads_trainer(eight_devices):
    bundle = get_model("llama-debug", dtype=jnp.float32)
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("fsdp", make_mesh(fsdp=8)), donate=False)
    d = mesh_descriptor(t)
    assert d["axes"] == {"fsdp": 8}
    assert d["device_count"] == 8
    assert d["strategy"] == "fsdp"
    assert d["pp_stages"] == 1 and d["quant_block"] is None
    host = stamp_host_state({"global_step": 3}, t)
    assert host["mesh"] == d and host["precision_policy"] == "fp32"


# ---------------------------------------------------------------------------
# reshard restore through the policy-aware entry point
# ---------------------------------------------------------------------------

def _step_n(t, state, ids, n):
    batch = {k: jax.device_put(ids, t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    losses = []
    for _ in range(n):
        state, m = t.step_fn(state, batch)
        losses.append(float(m["loss"]))
    return state, losses


def test_reshard_restore_trajectory_via_entry_point(tmp_path, eight_devices):
    """The elastic acceptance pin, through ``restore_train_state`` (the
    stamped, policy- and mesh-aware entry point): save on mesh A
    (fsdp=8), restore on mesh B (fsdp=4, half the devices — a different
    dp/fsdp factorization), continue — the stitched trajectory equals the
    uninterrupted 8-device run at the documented tolerance, and the
    cross-mesh restore announces itself instead of silently resharding."""
    bundle = get_model("llama-debug", dtype=jnp.float32)
    opt = adamw_cosine(1e-3)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 512, (8, 16)))

    tg = Trainer(bundle=bundle, optimizer=opt,
                 plan=make_plan("fsdp", make_mesh(fsdp=8)), donate=False)
    _, golden = _step_n(tg, tg.init_state(0), ids, 4)

    t8 = Trainer(bundle=bundle, optimizer=opt,
                 plan=make_plan("fsdp", make_mesh(fsdp=8)), donate=False)
    state, first = _step_n(t8, t8.init_state(0), ids, 2)
    io = CheckpointIO(tmp_path / "exp")
    host = host_state_dict()
    host["global_step"] = 2
    io.save(state, stamp_host_state(host, t8))

    t4 = Trainer(bundle=bundle, optimizer=opt,
                 plan=make_plan("fsdp",
                                make_mesh(devices=jax.devices()[:4],
                                          fsdp=4)),
                 donate=False)
    import logging

    records = []
    handler = logging.Handler()
    handler.emit = lambda rec: records.append(rec.getMessage())
    logging.getLogger(
        "distributed_training_guide_tpu.checkpoint.orbax_io"
    ).addHandler(handler)
    try:
        restored, host2 = restore_train_state(io, t4)
    finally:
        logging.getLogger(
            "distributed_training_guide_tpu.checkpoint.orbax_io"
        ).removeHandler(handler)
    assert any("cross-mesh restore" in m and "fsdp=8" in m and "fsdp=4" in m
               for m in records), records
    assert host2["global_step"] == 2
    assert host2["mesh"]["axes"] == {"fsdp": 8}   # the stamp round-trips
    leaf = jax.tree.leaves(restored.params)[0]
    assert len(leaf.sharding.mesh.devices.ravel()) == 4
    _, cont = _step_n(t4, restored, ids, 2)
    np.testing.assert_allclose(first + cont, golden, rtol=2e-4)


def test_quant_block_tiling_restore_fails_loudly(tmp_path):
    """adam8bit moments tiled at block 64 restored into a block-128
    policy: the per-block scale arrays have different shapes, so restore
    must refuse NAMING BOTH TILINGS — not die inside TensorStore, not
    fall back through the retention chain."""
    bundle = get_model("llama-debug", dtype=jnp.float32)
    p64 = PrecisionPolicy(name="adam8bit", quantize_moments=True,
                          block_size=64)
    t64 = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                  precision=p64, donate=False)
    state = t64.init_state(0)
    io = CheckpointIO(tmp_path / "exp")
    host = host_state_dict()
    host["global_step"] = 1
    io.save(state, stamp_host_state(host, t64))

    t128 = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                   precision="adam8bit", donate=False)
    with pytest.raises(ReshardIncompatibleError, match="block size 64"):
        restore_train_state(io, t128)
    with pytest.raises(ReshardIncompatibleError, match="block size 128"):
        restore_train_state(io, t128)
    # the matching tiling restores fine
    t64b = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                   precision=p64, donate=False)
    restored, host2 = restore_train_state(io, t64b)
    assert host2["global_step"] == 1


def test_pp_stage_split_stamp_fails_loudly(tmp_path):
    """A checkpoint stamped under a 2-stage pipeline split refuses to
    restore into a 1-stage run, naming both layouts (the stage-owned
    layer layout is not reshard-compatible)."""
    bundle = get_model("llama-debug", dtype=jnp.float32)
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), donate=False)
    state = t.init_state(0)
    io = CheckpointIO(tmp_path / "exp")
    host = stamp_host_state({**host_state_dict(), "global_step": 1}, t)
    host["mesh"] = {"axes": {"pp": 2, "fsdp": 4}, "device_count": 8,
                    "strategy": "pp_fsdp", "pp_stages": 2,
                    "quant_block": None}
    io.save(state, host)
    with pytest.raises(ReshardIncompatibleError,
                       match="2-stage pipeline split"):
        restore_train_state(io, t)


def test_fp32_fallback_reencode_under_mesh_change(tmp_path, eight_devices):
    """The fp32->policy re-encode path re-verified under a mesh change:
    an fp32 checkpoint saved on fsdp=8 restores into an adam8bit run on
    fsdp=4 — re-encoded into quantized storage with the logged warning,
    on the NEW mesh, and immediately trainable."""
    bundle = get_model("llama-debug", dtype=jnp.float32)
    opt = adamw_cosine(1e-3)
    t8 = Trainer(bundle=bundle, optimizer=opt,
                 plan=make_plan("fsdp", make_mesh(fsdp=8)), donate=False)
    state = t8.init_state(0)
    io = CheckpointIO(tmp_path / "exp")
    host = host_state_dict()
    host["global_step"] = 1
    io.save(state, stamp_host_state(host, t8))

    t4 = Trainer(bundle=bundle, optimizer=opt,
                 plan=make_plan("fsdp",
                                make_mesh(devices=jax.devices()[:4],
                                          fsdp=4)),
                 precision="adam8bit", donate=False)
    restored, host2 = restore_train_state(io, t4)
    assert host2["global_step"] == 1
    from distributed_training_guide_tpu.train.precision import Quantized

    quant_leaves = [x for x in jax.tree.leaves(
        restored.opt_state, is_leaf=lambda x: isinstance(x, Quantized))
        if isinstance(x, Quantized)]
    assert quant_leaves, "moments were not re-encoded into int8 storage"
    leaf = jax.tree.leaves(restored.params)[0]
    assert len(leaf.sharding.mesh.devices.ravel()) == 4
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 512, (8, 16)))
    _, losses = _step_n(t4, restored, ids, 1)
    assert np.isfinite(losses[0])


# ---------------------------------------------------------------------------
# world agreement protocol (pure files, no jax)
# ---------------------------------------------------------------------------

def test_membership_liveness(tmp_path):
    a = el.SliceMember(tmp_path, "a")
    b = el.SliceMember(tmp_path, "b")
    a.beat()
    b.beat()
    assert el.live_members(tmp_path, 5.0) == ["a", "b"]
    # a stale payload timestamp ages out; retire removes immediately
    assert el.live_members(tmp_path, 5.0,
                           now=time.time() + 10) == []
    b.retire()
    assert el.live_members(tmp_path, 5.0) == ["a"]


def test_world_agreement_barrier(tmp_path):
    a = el.WorldNegotiator(tmp_path, "a", ack_timeout_s=5.0)
    b = el.WorldNegotiator(tmp_path, "b")
    got = {}
    t = threading.Thread(target=lambda: got.update(b=b.follow(0, 5.0)))
    t.start()
    world = a.propose_and_agree(["a", "b"], "start")
    t.join()
    assert world["world_id"] == 1 and world["members"] == ["a", "b"]
    assert got["b"]["world_id"] == 1
    events = el.read_events(tmp_path)
    assert len(events) == 1
    assert events[0]["event"] == "renegotiated"
    assert events[0]["old_world"] is None
    assert events[0]["new_world"]["members"] == ["a", "b"]
    assert events[0]["trigger"] == "start"
    assert "wall_time" in events[0]


def test_world_agreement_drops_stragglers(tmp_path):
    """A proposed member that never acks is presumed dead: the leader
    re-proposes without it under a fresh world_id — the renegotiation a
    dead slice triggered is never wedged by that same dead slice."""
    a = el.WorldNegotiator(tmp_path, "a", ack_timeout_s=0.3)
    b = el.WorldNegotiator(tmp_path, "b")
    t = threading.Thread(target=lambda: b.follow(0, 5.0))
    t.start()
    world = a.propose_and_agree(["a", "b", "ghost"], "start")
    t.join()
    assert world["members"] == ["a", "b"]
    assert world["world_id"] >= 2          # the ghost cost one round


def test_world_agreement_single_member(tmp_path):
    a = el.WorldNegotiator(tmp_path, "a", ack_timeout_s=0.2)
    world = a.propose_and_agree(["a"], "slice_lost")
    assert world["members"] == ["a"] and world["world_id"] == 1


def test_stale_ack_is_id_fenced(tmp_path):
    """An ack file left by a previous incarnation names an old world_id
    and cannot satisfy a newer proposal's barrier."""
    a = el.WorldNegotiator(tmp_path, "a", ack_timeout_s=0.3)
    # publish world 1 so the next proposal is id 2
    a.propose_and_agree(["a"], "start")
    # preset a stale ack for b naming world 1
    el._write_json_atomic(tmp_path / "world.ack.b.json",
                          {"world_id": 1, "member": "b"})
    world = a.propose_and_agree(["a", "b"], "slice_joined")
    # b never acked id >= 2, so it was dropped despite the stale file
    assert world["members"] == ["a"]


def test_fenced_out_member_raises(tmp_path):
    a = el.WorldNegotiator(tmp_path, "a", ack_timeout_s=0.2)
    a.propose_and_agree(["a"], "slice_lost")     # world excludes b
    b = el.WorldNegotiator(tmp_path, "b")
    with pytest.raises(el.FencedOutError):
        b.follow(0, 0.5)


def test_member_helper_slice_loss_fault(tmp_path, monkeypatch):
    """DTG_FAULT_SLICE_LOSS kills the member helper WITHOUT retiring its
    file — the no-cleanup slice loss the liveness timeout ages out."""
    monkeypatch.setenv(faults.ENV_SLICE_LOSS, "b@3")
    rc = el.run_member(tmp_path, "b", interval_s=0.01, max_beats=50)
    assert rc == 1
    payload = json.loads(
        (tmp_path / el.MEMBERS_DIR / "b.json").read_text())
    assert payload["beats"] == 3                  # died at its 3rd beat
    # the file is still there (no cleanup): only liveness age removes it
    assert el.live_members(tmp_path, 60.0) == ["b"]
    assert el.live_members(tmp_path, 0.0, now=time.time() + 1) == []


def test_member_helper_fenced_out_exits_cleanly(tmp_path):
    """A member the fleet once HELD exits when a newer world excludes
    it; a stale world that PREDATES the member's join must NOT fence it
    (the joiner keeps beating until the leader admits it)."""
    # stale world excluding b: the joiner is not fenced, runs out its
    # beats and retires normally
    el._write_json_atomic(tmp_path / el.WORLD_FILE,
                          {"world_id": 5, "members": ["a"]})
    rc = el.run_member(tmp_path, "b", interval_s=0.001, max_beats=20)
    assert rc == 0
    assert not (tmp_path / el.MEMBERS_DIR / "b.json").exists()  # retired
    # now b becomes a member, then the fleet moves on without it
    el._write_json_atomic(tmp_path / el.WORLD_FILE,
                          {"world_id": 6, "members": ["a", "b"]})
    done = {}
    t = threading.Thread(target=lambda: done.update(
        rc=el.run_member(tmp_path, "b", interval_s=0.01, max_beats=500)))
    t.start()
    time.sleep(0.1)                       # b observes its membership
    el._write_json_atomic(tmp_path / el.WORLD_FILE,
                          {"world_id": 7, "members": ["a"]})
    t.join(timeout=10)
    assert done.get("rc") == 0
    assert not (tmp_path / el.MEMBERS_DIR / "b.json").exists()  # retired


# ---------------------------------------------------------------------------
# worker re-exec rendering
# ---------------------------------------------------------------------------

def test_render_worker_cmd_tokens():
    cmd = ["python", "train.py", "-b", "{world_batch}",
           "--note", "world={world_devices}"]
    out = el.render_worker_cmd(cmd, 4, global_batch=8)
    assert out == ["python", "train.py", "-b", "2", "--note", "world=4"]
    with pytest.raises(ValueError, match="elastic-global-batch"):
        el.render_worker_cmd(["-b", "{world_batch}"], 4)
    with pytest.raises(ValueError, match="not divisible"):
        el.render_worker_cmd(["-b", "{world_batch}"], 3, global_batch=8)


def test_worker_world_env_forces_device_count():
    env = {"XLA_FLAGS": "--xla_foo=1 "
                        "--xla_force_host_platform_device_count=8"}
    world = {"world_id": 3, "members": ["a", "b"]}
    el.worker_world_env(env, world, 4)
    assert env["XLA_FLAGS"] == \
        "--xla_foo=1 --xla_force_host_platform_device_count=4"
    assert env["DTG_WORLD_ID"] == "3"
    assert env["DTG_WORLD_MEMBERS"] == "a,b"
    assert env["DTG_WORLD_DEVICES"] == "4"


# ---------------------------------------------------------------------------
# the supervisor slice-loss chaos drill (subprocess; slow: two training
# incarnations at different device counts + a golden run)
# ---------------------------------------------------------------------------

MP_COMPILE_CACHE = os.path.join(
    os.environ.get("TMPDIR", "/tmp"), "dtg_tpu_mp_compile_cache")
CH02 = REPO / "02-distributed-data-parallel" / "train_llm.py"
TRAIN_FLAGS = ["-m", "llama-debug", "-d", "synthetic:60000", "-s", "64",
               "--num-epochs", "2", "--log-freq", "1"]


def _losses_by_step(text: str) -> dict:
    import ast

    out = {}
    for line in text.splitlines():
        at = line.find("INFO:{")
        if at >= 0:
            try:
                d = ast.literal_eval(line[at + 5:])
            except (ValueError, SyntaxError):
                continue
            if isinstance(d, dict) and "global_step" in d:
                out[d["global_step"]] = d["running_loss"]
    return out


def _drill_env(**extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update(JAX_PLATFORMS="cpu",
               JAX_COMPILATION_CACHE_DIR=MP_COMPILE_CACHE)
    env.update(extra)
    return env


@pytest.mark.slow
@pytest.mark.chaos
def test_supervisor_slice_loss_renegotiates_and_resumes(tmp_path):
    """THE slice-loss drill: a 2-slice world (4 devices each, global
    batch held at 8 via {world_batch}) loses its peer slice mid-run
    (DTG_FAULT_SLICE_LOSS kills the member helper without cleanup); the
    supervisor notices via membership liveness, SIGTERMs the worker,
    renegotiates to the 1-slice world (barrier'd world.json), re-execs
    the worker with 4 forced devices, and the run resumes from the last
    checkpoint ONTO THE SMALLER MESH — no manual intervention. Every
    step logged by any incarnation must match the uninterrupted golden
    trajectory (rtol covers the cross-mesh reduction-order change), and
    elastic.jsonl must record the 2->1 membership timeline."""
    n_steps = 60        # checkpoint-every-2 pacing keeps the run long
    os.makedirs(MP_COMPILE_CACHE, exist_ok=True)
    # golden: uninterrupted 8-device run at global batch 8 (no -e, so no
    # checkpoint I/O — pure trajectory)
    golden_proc = subprocess.run(
        [sys.executable, str(CH02), *TRAIN_FLAGS, "-b", "1",
         "--max-steps", str(n_steps),
         "--save-dir", str(tmp_path / "golden")],
        capture_output=True, text=True, timeout=420, cwd=REPO,
        env=_drill_env(
            XLA_FLAGS="--xla_force_host_platform_device_count=8"))
    assert golden_proc.returncode == 0, \
        (golden_proc.stdout + golden_proc.stderr)[-3000:]
    golden = _losses_by_step(golden_proc.stdout + golden_proc.stderr)
    assert set(golden) == set(range(1, n_steps + 1))

    coord = tmp_path / "coord"
    sup_logs = tmp_path / "sup"
    work = tmp_path / "work"
    # the peer slice: beats until killed. The drill kills it with
    # SIGKILL — the same no-cleanup death DTG_FAULT_SLICE_LOSS injects
    # (unit-pinned above) — but ANCHORED to the step-2 checkpoint
    # publishing, so the loss always lands where the resume has
    # something to resume from whatever this machine's compile time is.
    member = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_training_guide_tpu.launch.elastic",
         "--member", "slice1", "--dir", str(coord),
         "--interval", "0.1", "--max-beats", "100000"],
        env=_drill_env(),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

    def kill_member_after_checkpoint():
        deadline = time.time() + 400
        ckpt = work / "drill" / "checkpoint-2"
        while time.time() < deadline and not ckpt.exists():
            time.sleep(0.2)
        time.sleep(0.5)                    # let state.json publish too
        member.kill()                      # SIGKILL: the slice is gone

    killer = threading.Thread(target=kill_member_after_checkpoint,
                              daemon=True)
    try:
        killer.start()
        cmd = [sys.executable, "-m",
               "distributed_training_guide_tpu.launch.supervisor",
               "--max-restarts", "2", "--restart-backoff", "0.05",
               "--log-dir", str(sup_logs),
               "--elastic-dir", str(coord), "--slice-name", "slice0",
               "--devices-per-slice", "4", "--liveness-timeout", "1.5",
               "--elastic-global-batch", "8", "--",
               sys.executable, str(CH02), *TRAIN_FLAGS,
               "-b", "{world_batch}", "--max-steps", str(n_steps),
               "--ckpt-freq", "2", "-e", "drill",
               "--save-dir", str(work)]
        # pace the worker with the slow-NFS fault (0.25s per checkpoint
        # save): the slice loss lands at checkpoint-2 and detection takes
        # ~2x the liveness timeout — a warm-cache run without pacing can
        # finish all its steps inside that window and the drill would
        # race instead of drilling
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=540, cwd=REPO,
            env=_drill_env(**{faults.ENV_SAVE_LATENCY_S: "0.25"}))
    finally:
        member.kill()
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-4000:]

    # the membership timeline: world 2 members -> world 1 member
    events = el.read_events(coord)
    assert events, "no elastic.jsonl events recorded"
    assert events[0]["new_world"]["members"] == ["slice0", "slice1"]
    lost = [e for e in events
            if e["new_world"]["members"] == ["slice0"]]
    assert lost, events
    assert lost[0]["old_world"]["members"] == ["slice0", "slice1"]
    assert lost[0]["trigger"] == "slice_lost"
    assert "renegotiation (slice_lost)" in out

    # both worlds really ran: 8 forced devices then 4, batch 1 then 2
    attempts = sorted(sup_logs.glob("attempt_*"))
    assert len(attempts) >= 2
    assert "world 1 agreed" in out and "8 devices" in out
    assert "4 devices" in out

    # trajectory: every step any incarnation logged matches golden
    stitched = {}
    for d in attempts:
        text = (d / "stdout.log").read_text() \
            + (d / "stderr.log").read_text()
        stitched.update(_losses_by_step(text))
    last = (attempts[-1] / "stdout.log").read_text() \
        + (attempts[-1] / "stderr.log").read_text()
    assert "Resumed=True" in last          # the shrink resumed, not reran
    assert set(stitched) == set(range(1, n_steps + 1))
    for step, loss in stitched.items():
        np.testing.assert_allclose(loss, golden[step], rtol=2e-4,
                                   err_msg=f"step {step}")
