"""Fault-injection toy for the elastic supervisor.

Counterpart of the reference's ``related-topics/elastic-training/toy.py``
(random crashes exercising torchrun's restart machinery — "No GPU required").
Here: a fake training loop that checkpoints to a state file, randomly raises,
and resumes from the state file when the supervisor restarts it. Verification
is the same as the reference's: inspect ``attempt_*/error.json`` and the logs.

Run:
    python -m distributed_training_guide_tpu.launch.supervisor \
        --max-restarts 5 --log-dir /tmp/elastic-toy -- \
        python related-topics/elastic-training/toy.py --state /tmp/elastic-toy/state.json
"""
import argparse
import json
import os
import random
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent.parent))

from distributed_training_guide_tpu.launch.errors import record


@record
def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--state", default="/tmp/elastic-toy-state.json")
    parser.add_argument("--total-steps", type=int, default=50)
    parser.add_argument("--crash-prob", type=float, default=0.08)
    args = parser.parse_args()

    step = 0
    if os.path.exists(args.state):
        with open(args.state) as fp:
            step = json.load(fp)["step"]
        print(f"resumed at step {step}", flush=True)

    random.seed(os.getpid())
    while step < args.total_steps:
        time.sleep(0.05)
        step += 1
        print(f"step {step}", flush=True)
        with open(args.state, "w") as fp:
            json.dump({"step": step}, fp)
        if random.random() < args.crash_prob:
            raise ValueError(f"injected fault at step {step}")
    print("training complete", flush=True)


if __name__ == "__main__":
    main()
