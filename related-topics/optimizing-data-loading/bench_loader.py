"""Loader-throughput microbenchmark: python vs native (C++) batch assembly.

TPU-native counterpart of the reference chapter's num_workers/prefetch_factor
measurements (``related-topics/optimizing-data-loading/README.md:24-102``):
instead of sweeping DataLoader knobs, compare the two batch-assembly paths
this framework ships — numpy gather (``data/loader.py``) and the C++
mmap/prefetch loader (``csrc/token_loader.cpp``) — and report tokens/s of
pure host-side work. Run it on the machine whose ``time/data`` timer looks
suspicious; if both paths are far above your model's tokens/s, the loader is
not your bottleneck (the usual verdict — batch assembly is a gather, not
per-example python).

Usage: python bench_loader.py [--seqs 40000] [--seq-len 2048] [--batch 64]
Prints one JSON line per path.
"""
import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent.parent))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seqs", type=int, default=40000)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--batches", type=int, default=200)
    args = p.parse_args()

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_training_guide_tpu.data import ShardedBatchLoader
    from distributed_training_guide_tpu.parallel import make_mesh

    dataset = np.random.RandomState(0).randint(
        0, 32000, (args.seqs, args.seq_len), dtype=np.int32)
    mesh = make_mesh(devices=jax.devices())
    sharding = NamedSharding(mesh, P(("dp", "fsdp", "ep"), None))

    if args.seqs < 2 * args.batch:
        p.error(f"--seqs must be >= 2*batch ({2 * args.batch}) for a warmup "
                f"batch plus at least one timed batch")

    for native in (False, True):
        loader = ShardedBatchLoader(dataset, args.batch, sharding,
                                    seed=0, native=native)
        try:
            it = loader.epoch_batches()
            next(it)  # absorb first-batch setup (mmap dump, prefetch fill)
            n = min(args.batches, len(loader) - 1)
            t0 = time.perf_counter()
            for _ in range(n):
                batch = next(it)
            jax.block_until_ready(batch["input_ids"])
            dt = time.perf_counter() - t0
            used_native = loader._native is not None  # before close() clears it
        finally:
            loader.close()  # the native path holds a dataset-sized temp file
        tok = n * args.batch * args.seq_len
        print(json.dumps({
            "path": "native_cpp" if used_native else "python_numpy",
            "tokens_per_s": round(tok / dt),
            "batches_per_s": round(n / dt, 1),
            "ms_per_batch": round(1000 * dt / n, 3),
        }), flush=True)


if __name__ == "__main__":
    main()
