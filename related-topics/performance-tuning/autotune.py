#!/usr/bin/env python3
"""Walk the measured lever ladder (README.md here) on YOUR model and report
the winning flag set.

The reference tunes these knobs by hand, chapter by chapter (batch in its
``02``, activation checkpointing + offload in ``04``/``05``); this walks
them automatically the way the round-4 bench sweep was run: every probe in
a kill-able subprocess (an OOM or a pool stall costs one probe, never the
walk), keep a lever only if measured time-per-token improves, re-walk batch
last because every earlier lever moves the HBM knee.

    python related-topics/performance-tuning/autotune.py -m llama-650m -s 2048
    python related-topics/performance-tuning/autotune.py -m hf:/ckpts/my-model --dry-run

Output: one JSON line per probe, then a final ``best`` line whose ``flags``
paste directly onto any chapter's ``train_llm.py`` command.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import statistics
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
RUNNER = os.path.join(REPO, "01-single-chip", "train_llm.py")

# the measured-order ladder (README.md table); each entry: (name, extra flags)
REMAT_LADDER = ["all", "attn", "attn_mlp"]


def parse_step_ms(out: str) -> float | None:
    """Median of the post-compile log windows (the loop logs
    `'time/total': <ms>` per window; the FIRST window carries compile +
    warmup and is dropped — the median over the rest is what is robust to
    a single slow window on a jittery pool)."""
    hits = [float(h) for h in re.findall(r"'time/total': ([0-9.]+)", out)]
    windows = hits[1:] if len(hits) > 1 else hits
    if not windows:
        return None
    return float(statistics.median(windows))


def parse_mfu(out: str) -> float | None:
    hits = re.findall(r"'mfu': ([0-9.eE+-]+)", out)
    return float(hits[-1]) if hits else None


def classify_failure(err: str) -> str:
    """Same canonical XLA markers as bench.py's child classifier: device HBM
    exhaustion is retire-the-config, pool-capacity rejection is retryable."""
    if ("Out of memory" in err or "Largest program allocations" in err
            or "Error allocating device buffer" in err):
        return "oom"
    if "RESOURCE_EXHAUSTED" in err:
        return "pool_exhausted"
    return "failed"


def probe_cmd(args, batch: int, flags: list[str], save_dir: str) -> list[str]:
    tokens = batch * args.seq * (args.steps + 2)
    # log-freq 4 everywhere: the loop drains banked losses at every log
    # boundary, so a smaller log window would silently cap --fence-every 4
    # at depth 2 — the probe must RUN at the depth it recommends
    return [sys.executable, RUNNER, "-m", args.model,
            "-d", f"synthetic:{max(tokens * 2, 20000)}",
            "-s", str(args.seq), "-b", str(batch),
            "--num-epochs", "1", "--max-steps", str(args.steps),
            "--log-freq", "4", "--save-dir", save_dir, *flags]


def run_probe(args, batch: int, flags: list[str]) -> dict:
    """One config in a kill-able subprocess -> {ms, mfu} | {error}."""
    with tempfile.TemporaryDirectory() as d:
        t0 = time.time()
        try:
            proc = subprocess.run(
                probe_cmd(args, batch, flags, d), capture_output=True,
                text=True, timeout=args.budget)
        except subprocess.TimeoutExpired:
            return {"error": "stalled"}
        out = proc.stdout + proc.stderr
        if proc.returncode != 0:
            return {"error": classify_failure(out)}
        ms = parse_step_ms(out)
        if ms is None:
            return {"error": "no_result"}
        return {"ms": ms, "mfu": parse_mfu(out),
                "wall_s": round(time.time() - t0, 1)}


def plan_walk(args) -> list[dict]:
    """The probe sequence, data only (what --dry-run prints). Each entry:
    {name, batch, flags}. The walk evaluates them statefully — a lever is
    kept only if it improved — so later entries here show the flags they
    would add, not the final composition."""
    steps = [{"name": "baseline", "batch": args.batch, "flags": []}]
    steps.append({"name": "fence4", "batch": args.batch,
                  "flags": ["--fence-every", "4"]})
    for policy in REMAT_LADDER:
        steps.append({"name": f"remat_{policy}", "batch": args.batch,
                      "flags": ["--checkpoint-activations",
                                "--remat-policy", policy]})
    steps.append({"name": "adafactor", "batch": args.batch,
                  "flags": ["--optimizer", "adafactor"]})
    # re-walk the remat ladder AFTER adafactor: the measured headline
    # (fence4 + adafactor + attn_mlp, BENCH.md) is only reachable this way —
    # attn_mlp's bigger saved set needs the HBM adafactor frees, so its
    # first probe (AdamW still active) can OOM and must get a second chance.
    # The walk skips any retry whose composed config it already measured.
    for policy in REMAT_LADDER[1:]:
        steps.append({"name": f"remat_{policy}_after_adafactor",
                      "batch": args.batch,
                      "flags": ["--checkpoint-activations",
                                "--remat-policy", policy]})
    steps.append({"name": "loss_chunks8", "batch": args.batch,
                  "flags": ["--loss-chunks", "8"]})
    b = args.batch
    while b < args.batch * 4:
        b *= 2
        steps.append({"name": f"batch_{b}", "batch": b, "flags": []})
    return steps


def compose_flags(kept: list[str], step_name: str,
                  step_flags: list[str]) -> list[str]:
    """Compose a probe's flag set from the kept levers plus the step's.

    Remat rungs REPLACE the kept policy, not stack with it: strip the kept
    3-token segment (``--checkpoint-activations --remat-policy <p>``)
    wherever it sits and keep everything around it — truncating at the
    segment would silently drop levers kept after it (e.g. adafactor,
    turning the post-adafactor attn_mlp retry into a mislabeled re-probe
    of the config that already OOMed)."""
    if step_name.startswith("remat_") and "--remat-policy" in kept:
        i = kept.index("--checkpoint-activations")
        return kept[:i] + kept[i + 3:] + step_flags
    return kept + step_flags


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-m", "--model", required=True)
    p.add_argument("-s", "--seq", type=int, default=2048)
    p.add_argument("-b", "--batch", type=int, default=8)
    p.add_argument("--steps", type=int, default=12,
                   help="training steps per probe; the LAST 4-step log "
                        "window is what gets measured (post-compile, "
                        "post-warmup), so keep this a multiple of 4 >= 12")
    p.add_argument("--budget", type=int, default=600,
                   help="seconds per probe before it is killed (compile "
                        "included)")
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args()

    plan = plan_walk(args)
    if args.dry_run:
        for s in plan:
            print(json.dumps(s))
        return

    def emit(rec):
        print(json.dumps(rec), flush=True)

    best = None        # (time-per-token, record)
    kept_flags: list[str] = []
    kept_batch = args.batch

    def tpt(ms, batch):
        return ms / (batch * args.seq)

    probed = set()
    for step in plan:
        name, batch = step["name"], max(step["batch"], kept_batch)
        if step["name"].startswith("batch_"):
            batch = step["batch"]
        flags = compose_flags(kept_flags, name, step["flags"])
        key = (tuple(flags), batch)
        if key in probed:   # e.g. a post-adafactor remat retry that already won
            emit({"probe": name, "status": "skipped_already_measured"})
            continue
        probed.add(key)
        res = run_probe(args, batch, flags)
        if res.get("error") in ("pool_exhausted", "stalled"):
            # transient pool conditions, not properties of the config
            # (classify_failure's distinction): one retry after a pause
            emit({"probe": name, "batch": batch, "flags": flags, **res,
                  "retrying": True})
            time.sleep(30)
            res = run_probe(args, batch, flags)
        rec = {"probe": name, "batch": batch, "flags": flags, **res}
        emit(rec)
        if "error" in res:
            continue
        score = tpt(res["ms"], batch)
        if best is None or score < best[0]:
            best = (score, rec)
            kept_flags, kept_batch = flags, batch
    if best is None:
        emit({"best": None, "error": "no probe produced a result"})
        sys.exit(2)
    emit({"best": best[1]["probe"], "batch": best[1]["batch"],
          "flags": " ".join(best[1]["flags"]),
          "step_ms": best[1]["ms"], "mfu": best[1].get("mfu")})


if __name__ == "__main__":
    main()
